"""Compressed gradient all-reduce over a mesh axis (``parallel.grad_allreduce``).

SimCLR's large-batch recipe is communication-bound the moment it leaves one
chip: every step all-reduces the full fp32 gradient pytree, and at multihost
scale that crosses DCN where bandwidth — not the MXU — sets the step floor
(EQuARX, PAPERS.md). This module is the drop-in replacement for the
``jax.lax.psum(grads, DATA_AXIS)`` sites in ``parallel/steps.py`` and
``parallel/tp.py``, selected by the ``parallel.grad_allreduce`` knob:

  * ``exact`` — the plain fp32 ``psum`` (default; bitwise-identical to the
    pre-knob behavior).
  * ``bf16``  — cast → ``psum`` → cast back. Halves wire bytes; the mantissa
    truncation is deterministic (biased toward zero) but tiny relative to
    LARS' trust-ratio normalization.
  * ``int8``  — bucketed stochastic-rounding quantization, ~3.98x fewer wire
    bytes than fp32 at the default bucket size (see
    :func:`allreduce_wire_bytes`). Unbiased: E[dequant(quant(x))] = x.

The int8 reduction keeps the WIRE format int8 end to end by decomposing the
all-reduce the way a ring all-reduce does — a reduce-scatter phase and an
all-gather phase — with the summation lifted out of the network:

  1. flatten the pytree to one fp32 vector, pad, and cut into fixed-size
     buckets; quantize each bucket as ``q = floor(x / scale + u)`` with
     ``scale = amax(|bucket|) / 127`` and ``u ~ Uniform[0, 1)`` drawn from
     the per-step PRNG key (stochastic rounding — the estimator is unbiased
     and, because the key is threaded from the train step, reproducible);
  2. *scatter*: ``all_to_all`` the int8 buckets (plus the tiny fp32 scale
     vector) so each device receives every peer's copy of the bucket range
     it owns — this is ``psum_scatter`` with the sum deferred, because int8
     partial sums would overflow and carry no shared scale;
  3. *local dequant-accumulate*: each device sums its owned range in fp32;
  4. *gather*: requantize the reduced range (fresh stochastic rounding, a
     folded key) and ``all_gather`` it back as int8; every device
     dequantizes and unflattens into the original pytree structure.

Both phases ship int8 payloads; the only fp32 on the wire is one scale per
``bucket_size`` elements (1/256 overhead at the default 1024).

Ordering contract (L2): compression replaces the gradient ``psum`` and
therefore runs BEFORE the optimizer — quantize-before-LARS, never after.
LARS (``ops/lars.py``) rescales each layer by ``||p|| / ||g||``; feeding it
the identical dequantized gradient on every replica keeps the trust ratios
replica-identical, whereas quantizing the *update* after the trust ratio
would break that and compound the error through the momentum buffer.

TP note: compression applies to the DATA axis only. Model-axis collectives
(the activation gathers/reduce-scatters inside ``models/heads.py`` and the
head-gradient psums) stay exact — they carry activations, not gradients,
and sit on fast ICI, not DCN. ``tp.py`` folds its PRNG key with the data
axis index only, so model-axis replicas draw identical rounding noise and
replicated-parameter gradients stay bitwise identical across the model axis
after dequantization.

Overlap (``parallel.comm_overlap``): the single-shot paths above emit ONE
fused collective tail after the backward — nothing for XLA's latency-hiding
scheduler to interleave. ``comm_overlap=chunked`` (+ ``parallel.comm_chunks``)
instead cuts the flattened gradient into N layer-ordered chunks and reduces
each as an explicit software ring: ``jax.lax.ppermute`` reduce-scatter hops
(each hop ships one segment per device; for int8 the running partial sum is
requantized per hop in PR 4's bucket format, since int8 partials would
overflow and carry no shared scale) followed by ``ppermute`` all-gather hops
that forward each owner's payload verbatim. Chunk i's hops are
data-independent of chunk i+1's quant/dequant compute, so the scheduler can
overlap wire time with compute instead of serializing one tail. Per-chunk
PRNG keys are folded off the same ``KEY_FOLD_QUANT``-derived key
(``fold_in(key, chunk_idx)``), and ``comm_overlap=off`` routes through the
unmodified single-shot code paths — bitwise-identical to PR 4's behavior.
The gather phase forwards each reduced segment's bytes unchanged, so every
device dequantizes identical payloads and the replica-bitwise-identical
invariant survives chunking.

``comm_overlap=async`` goes one step further: the same ring decomposition
with the same per-chunk key schedule, but each bucket is assembled from only
the gradient leaves it spans (no global concatenate) and the rings are
issued in reverse bucket order — the order reverse-mode AD materializes
cotangents — so a tail bucket's wire hops are data-independent of the head
layers' backward matmuls and can be hidden under them (paired with the
staged backward in ``parallel/steps.py`` and the async-collective XLA flags
from ``parallel/mesh.py``). Because the bucket boundaries, wire format, and
``fold_in(key, chunk_idx)`` schedule are identical to ``chunked``, ``async``
hands LARS the *same dequantized gradient* (bitwise under int8) — only the
schedule changes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from simclr_tpu.parallel.mesh import axis_size

GRAD_ALLREDUCE_MODES = ("exact", "bf16", "int8")

# weight-storage formats for the serve tier (serve.weights): the same
# bucketed int8 format as the gradient wire path, but quantized ONCE at
# load time with DETERMINISTIC round-to-nearest — serving must be
# bitwise-repeatable across calls and across replicas, so the stochastic
# rounding that makes the gradient estimator unbiased is exactly wrong here
WEIGHT_QUANT_MODES = ("exact", "bf16", "int8")

# storage formats for the serve tier's retrieval corpus (serve.corpus_dtype):
# fp32 keeps the exact row-sharded matrix; int8 stores each shard's row block
# in the same deterministic bucket format as WEIGHT_QUANT_MODES' int8 (one
# fp32 scale per DEFAULT_BUCKET_SIZE elements, round-to-nearest) and
# dequantizes INSIDE the jitted scoring kernel — ~3.98x more rows per device
CORPUS_DTYPE_MODES = ("fp32", "int8")

# overlap strategy for the gradient all-reduce: "off" is the single-shot
# fused-collective path (bitwise-identical to PR 4), "chunked" decomposes it
# into parallel.comm_chunks independent ppermute rings XLA can overlap, and
# "async" additionally assembles each ring's bucket from only the leaves it
# spans — issued eagerly (last layers first) so the rings are data-ready
# while earlier layers' backward matmuls are still in flight
COMM_OVERLAP_MODES = ("off", "chunked", "async")

# default chunk count for comm_overlap=chunked: enough independent rings to
# hide wire latency under compute without shrinking messages below the
# bandwidth-efficient size at ResNet-18/50 gradient counts
DEFAULT_COMM_CHUNKS = 4

# upper bound on comm_chunks: beyond this the per-chunk segments at real
# model sizes fall under a bucket per device and padding dominates the wire
MAX_COMM_CHUNKS = 64

# elements per quantization bucket: one fp32 scale per bucket is the wire
# overhead (4/1024 -> 0.4%), while smaller buckets track the gradient's
# dynamic range more tightly. 1024 matches EQuARX's block size ballpark.
DEFAULT_BUCKET_SIZE = 1024

# fold_in tag forking the quantization PRNG stream off the train step's
# per-step rng: the augmentation stream splits the same rng, so the tag
# keeps the two streams disjoint (steps.py / tp.py use this constant)
KEY_FOLD_QUANT = 0x71

# int8 symmetric range [-127, 127]; -128 is left unused so the scale is the
# same magnitude in both directions
_QMAX = 127.0


def validate_mode(mode: str) -> str:
    """Reject unknown modes with the valid set spelled out (config + runtime)."""
    if mode not in GRAD_ALLREDUCE_MODES:
        raise ValueError(
            f"parallel.grad_allreduce must be one of {GRAD_ALLREDUCE_MODES}, "
            f"got {mode!r}"
        )
    return mode


def normalize_overlap(value) -> str:
    """Map YAML 1.1's bool reading of a bare ``off`` back to the mode name.

    ``yaml.safe_load("off")`` is False — which hits both conf files and
    ``parallel.comm_overlap=off`` CLI overrides — so the config boundary
    funnels through this before validation. Everything else passes through
    untouched for :func:`validate_overlap` to judge.
    """
    return "off" if value is False else value


def validate_overlap(overlap: str, chunks: int | None = None) -> str:
    """Reject unknown overlap modes / out-of-range chunk counts, with the
    valid set and range spelled out (config validation + runtime share this).
    """
    if overlap not in COMM_OVERLAP_MODES:
        raise ValueError(
            f"parallel.comm_overlap must be one of {COMM_OVERLAP_MODES}, "
            f"got {overlap!r}"
        )
    if chunks is not None:
        if int(chunks) != chunks or not (1 <= int(chunks) <= MAX_COMM_CHUNKS):
            raise ValueError(
                f"parallel.comm_chunks must be an int in [1, {MAX_COMM_CHUNKS}], "
                f"got {chunks!r}"
            )
    return overlap


def validate_weight_mode(mode: str) -> str:
    """Reject unknown serve.weights modes with the valid set spelled out."""
    if mode not in WEIGHT_QUANT_MODES:
        raise ValueError(
            f"serve.weights must be one of {WEIGHT_QUANT_MODES}, got {mode!r}"
        )
    return mode


def quantize_weight_buckets(
    flat: np.ndarray, bucket_size: int = DEFAULT_BUCKET_SIZE
) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic bucketed int8 quantization of a flat fp32 weight vector.

    The storage counterpart of :func:`_quantize`: the same bucket format
    (``scale = amax(|bucket|) / 127``, one fp32 scale per ``bucket_size``
    elements, all-zero buckets get scale 0) but **round-to-nearest** instead
    of stochastic rounding — weights are quantized once at engine load, and
    the serve tier's bitwise-repeatability contract requires the same input
    to produce the same int8 bytes on every load and every replica. Runs on
    the host (numpy) so load-time quantization allocates nothing on device.

    Returns ``(q, scales)``: ``q`` int8 of shape ``(n_buckets, bucket_size)``
    (tail zero-padded), ``scales`` fp32 of shape ``(n_buckets,)``.
    """
    flat = np.asarray(flat, np.float32).reshape(-1)
    n_buckets = -(-flat.size // bucket_size) if flat.size else 1
    x = np.zeros((n_buckets * bucket_size,), np.float32)
    x[: flat.size] = flat
    x = x.reshape(n_buckets, bucket_size)
    scale = (np.max(np.abs(x), axis=1) / _QMAX).astype(np.float32)
    safe = np.where(scale > 0.0, scale, 1.0)
    q = np.clip(np.rint(x / safe[:, None]), -_QMAX, _QMAX)
    return q.astype(np.int8), scale


def dequantize_weight_buckets(q, scales, n_elements: int):
    """Inverse of :func:`quantize_weight_buckets`; jnp, traceable under jit.

    This is the dequantize-on-load half of the serve tier's int8 weight
    path: it runs INSIDE the jitted forward, so HBM holds only the int8
    buckets + fp32 scales and the fp32 weights exist transiently per call.
    """
    x = q.astype(jnp.float32) * scales[:, None]
    return x.reshape(-1)[:n_elements]


def weight_storage_bytes(
    n_elements: int, mode: str, *, bucket_size: int = DEFAULT_BUCKET_SIZE
) -> int:
    """Analytic resident bytes for ``n_elements`` weights under a storage mode.

    The serve-tier sibling of :func:`allreduce_wire_bytes`: exact = 4 B/elem
    (fp32), bf16 = 2 B/elem, int8 = 1 B/elem padded to whole buckets plus
    one fp32 scale per bucket (~3.98x under fp32 at the default bucket
    size). Rendered per replica next to the measured gauge so the two can
    be reconciled.
    """
    validate_weight_mode(mode)
    n = int(n_elements)
    if mode == "exact":
        return 4 * n
    if mode == "bf16":
        return 2 * n
    n_buckets = -(-n // bucket_size) if n else 1
    return n_buckets * bucket_size + 4 * n_buckets


def validate_corpus_dtype(mode: str) -> str:
    """Reject unknown serve.corpus_dtype modes with the valid set spelled out."""
    if mode not in CORPUS_DTYPE_MODES:
        raise ValueError(
            f"serve.corpus_dtype must be one of {CORPUS_DTYPE_MODES}, got {mode!r}"
        )
    return mode


def corpus_storage_bytes(
    n_rows: int,
    dim: int,
    mode: str,
    *,
    shards: int = 1,
    bucket_size: int = DEFAULT_BUCKET_SIZE,
) -> int:
    """Analytic resident HBM bytes for a row-sharded retrieval corpus.

    The corpus sibling of :func:`weight_storage_bytes`. Rows are ceil-split
    over ``shards`` devices (each shard padded to the common per-shard row
    count R = ceil(n_rows / shards)); fp32 costs 4·R·d per shard, int8 packs
    each shard's (R·d,) block into whole buckets (1 B/elem) plus one fp32
    scale per bucket — ``4 / (1 + 4/bucket_size)`` ≈ 3.98x under fp32 at the
    default bucket size. ``hbm_state()`` reports the measured twin of this
    number so the two can be reconciled in tests and the runbook.
    """
    validate_corpus_dtype(mode)
    s = max(int(shards), 1)
    rows_per_shard = -(-int(n_rows) // s) if n_rows else 0
    elems = rows_per_shard * int(dim)
    if mode == "fp32":
        return 4 * elems * s
    n_buckets = -(-elems // bucket_size) if elems else 1
    return s * (n_buckets * bucket_size + 4 * n_buckets)


def _chunk_bounds(n_elements: int, chunks: int) -> list[tuple[int, int]]:
    """Ceil-split [0, n_elements) into up to ``chunks`` contiguous pieces.

    Layer order is preserved (chunk 0 holds the first layers' gradients);
    non-divisible sizes leave the last chunk short, and chunk counts larger
    than the element count simply produce fewer (single-element) chunks —
    never an empty ring.
    """
    size = -(-n_elements // max(int(chunks), 1))
    bounds, start = [], 0
    while start < n_elements:
        stop = min(start + size, n_elements)
        bounds.append((start, stop))
        start = stop
    return bounds


def allreduce_wire_bytes(
    n_elements: int,
    n_devices: int,
    mode: str,
    *,
    bucket_size: int = DEFAULT_BUCKET_SIZE,
    overlap: str = "off",
    chunks: int = 1,
) -> float:
    """Analytic per-device wire bytes for one gradient all-reduce.

    Bandwidth-optimal all-reduce moves ``2 * (n-1)/n * payload`` bytes
    through each device (reduce-scatter + all-gather, each ``(n-1)/n``);
    the mode sets the payload encoding:

      * exact: 4 bytes/element (fp32)
      * bf16:  2 bytes/element
      * int8:  1 byte/element + one fp32 scale per bucket (padding included,
        matching what :func:`grad_allreduce` actually ships)

    At the default bucket size int8 is ``4 / (1 + 4/1024)`` ≈ 3.98x smaller
    than exact — the microbenchmark (``scripts/allreduce_bench.py``) reports
    this next to measured ms/step.

    ``overlap="chunked"`` accounts the ring decomposition instead: each of
    the (up to) ``chunks`` pieces is padded to ``n`` segments (int8: to
    whole buckets per segment) and pays the same ``2 * (n-1)/n`` phase
    fraction on its padded payload — per-chunk padding is the only analytic
    cost of chunking, and it shrinks to zero at real gradient sizes.
    ``overlap="async"`` ships the exact same rings (the schedule, not the
    wire format, changes), so it shares the chunked accounting.
    """
    validate_mode(mode)
    validate_overlap(overlap, chunks if overlap != "off" else None)
    n = max(int(n_devices), 1)
    phase_fraction = 2.0 * (n - 1) / n
    if overlap != "off":
        total = 0.0
        for start, stop in _chunk_bounds(int(n_elements), int(chunks)):
            sz = stop - start
            if mode == "exact":
                total += 4.0 * (-(-sz // n) * n)
            elif mode == "bf16":
                total += 2.0 * (-(-sz // n) * n)
            else:
                nb = -(-sz // bucket_size)
                nb = -(-nb // n) * n
                total += float(nb * bucket_size) + 4.0 * nb
        return phase_fraction * total
    if mode == "exact":
        payload = 4.0 * n_elements
    elif mode == "bf16":
        payload = 2.0 * n_elements
    else:
        n_buckets = -(-int(n_elements) // bucket_size)  # ceil
        n_buckets = -(-n_buckets // n) * n  # pad bucket count to axis size
        payload = float(n_buckets * bucket_size) + 4.0 * n_buckets
    return phase_fraction * payload


def _quantize(x: jnp.ndarray, key: jax.Array) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Stochastic-rounding int8 quantization of (buckets, bucket_size) fp32.

    ``q = floor(x / scale + u)``, ``u ~ U[0, 1)``: E[q * scale] = x exactly,
    for any x — the rounding error is zero-mean noise, not bias. All-zero
    buckets (padding, dead layers) get scale 0 and quantize to 0.
    """
    amax = jnp.max(jnp.abs(x), axis=1)
    scale = amax / _QMAX
    safe = jnp.where(scale > 0.0, scale, 1.0)
    u = jax.random.uniform(key, x.shape, dtype=x.dtype)
    q = jnp.clip(jnp.floor(x / safe[:, None] + u), -_QMAX, _QMAX)
    return q.astype(jnp.int8), scale


def _int8_allreduce(
    flat: jnp.ndarray, axis_name: str, key: jax.Array, bucket_size: int
) -> jnp.ndarray:
    """Sum ``flat`` (fp32 vector) over ``axis_name`` with int8 wire format.

    Returns the fp32 vector of the same length; see the module docstring for
    the scatter / local-accumulate / gather decomposition.
    """
    n = axis_size(axis_name)
    n_elements = flat.shape[0]
    n_buckets = -(-n_elements // bucket_size)
    n_buckets = -(-n_buckets // n) * n
    padded = n_buckets * bucket_size
    x = jnp.zeros((padded,), flat.dtype).at[:n_elements].set(flat)
    x = x.reshape(n_buckets, bucket_size)

    q, scale = _quantize(x, key)

    # scatter: device d ends up holding every peer's quantized copy of
    # bucket range [d*chunk, (d+1)*chunk) — int8 on the wire, scales are the
    # only fp32 (one per bucket)
    chunk = n_buckets // n
    q_all = jax.lax.all_to_all(
        q.reshape(n, chunk, bucket_size), axis_name, split_axis=0, concat_axis=0
    )
    s_all = jax.lax.all_to_all(
        scale.reshape(n, chunk), axis_name, split_axis=0, concat_axis=0
    )

    # local dequant-accumulate: the deferred sum of the reduce-scatter
    reduced = jnp.sum(
        q_all.astype(flat.dtype) * s_all[:, :, None], axis=0
    )

    # gather: requantize the reduced chunk (fresh rounding noise from a
    # folded key) and all_gather it back as int8
    q2, s2 = _quantize(reduced, jax.random.fold_in(key, 1))
    q2_all = jax.lax.all_gather(q2, axis_name, axis=0, tiled=True)
    s2_all = jax.lax.all_gather(s2, axis_name, axis=0, tiled=True)

    out = q2_all.astype(flat.dtype) * s2_all[:, None]
    return out.reshape(-1)[:n_elements]


def _ring_chunk_allreduce(
    flat: jnp.ndarray,
    axis_name: str,
    mode: str,
    key: jax.Array | None,
    bucket_size: int,
) -> jnp.ndarray:
    """Sum one fp32 chunk over ``axis_name`` as an explicit ppermute ring.

    Reduce-scatter phase: hop t ships each device's running partial sum of
    one segment to the next ring neighbor (int8: requantized per hop in the
    bucket format — int8 partial sums would overflow and carry no shared
    scale); after n-1 hops device d owns the fully-reduced segment
    ``(d+1) % n``. All-gather phase: the owner's payload (int8 buckets +
    scales, or the raw wire-dtype segment) is forwarded VERBATIM around the
    ring, so every device dequantizes identical bytes and the result is
    bitwise identical across the axis. Returns the fp32 chunk of the input
    length.
    """
    n = axis_size(axis_name)
    if n == 1:
        return flat
    n_elements = flat.shape[0]
    d = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    if mode == "int8":
        n_buckets = -(-n_elements // bucket_size)
        n_buckets = -(-n_buckets // n) * n
        seg = n_buckets // n
        x = jnp.zeros((n_buckets * bucket_size,), flat.dtype).at[:n_elements].set(flat)
        x = x.reshape(n, seg, bucket_size)
    else:
        wire_dtype = jnp.bfloat16 if mode == "bf16" else flat.dtype
        seg = -(-n_elements // n)
        x = jnp.zeros((n * seg,), flat.dtype).at[:n_elements].set(flat)
        x = x.reshape(n, seg).astype(wire_dtype)

    # reduce-scatter hops: acc starts as the local copy of segment d and
    # walks the ring accumulating each neighbor's contribution
    acc = jnp.take(x, d, axis=0)
    for t in range(n - 1):
        if mode == "int8":
            q, s = _quantize(acc, jax.random.fold_in(key, 2 + t))
            q = jax.lax.ppermute(q, axis_name, perm)
            s = jax.lax.ppermute(s, axis_name, perm)
            recv = q.astype(flat.dtype) * s[:, None]
        else:
            recv = jax.lax.ppermute(acc, axis_name, perm)
        acc = recv + jnp.take(x, (d - t - 1) % n, axis=0)

    # all-gather hops: the reduced segment owned here is quantized once
    # (fresh rounding noise, the same fold tag the single-shot gather uses)
    # and its bytes forwarded unchanged n-1 times
    owned = (d + 1) % n
    if mode == "int8":
        cur_q, cur_s = _quantize(acc, jax.random.fold_in(key, 1))
        out_q = jnp.zeros((n,) + cur_q.shape, cur_q.dtype).at[owned].set(cur_q)
        out_s = jnp.zeros((n,) + cur_s.shape, cur_s.dtype).at[owned].set(cur_s)
        for t in range(n - 1):
            cur_q = jax.lax.ppermute(cur_q, axis_name, perm)
            cur_s = jax.lax.ppermute(cur_s, axis_name, perm)
            idx = (owned - t - 1) % n
            out_q = out_q.at[idx].set(cur_q)
            out_s = out_s.at[idx].set(cur_s)
        out = out_q.astype(flat.dtype) * out_s[:, :, None]
    else:
        cur = acc
        out = jnp.zeros((n,) + acc.shape, acc.dtype).at[owned].set(acc)
        for t in range(n - 1):
            cur = jax.lax.ppermute(cur, axis_name, perm)
            out = out.at[(owned - t - 1) % n].set(cur)
        out = out.astype(flat.dtype)
    return out.reshape(-1)[:n_elements]


def grad_allreduce(
    grads,
    axis_name: str,
    mode: str = "exact",
    *,
    key: jax.Array | None = None,
    bucket_size: int = DEFAULT_BUCKET_SIZE,
    overlap: str = "off",
    chunks: int = DEFAULT_COMM_CHUNKS,
):
    """All-reduce (sum) a gradient pytree over ``axis_name``.

    Drop-in for ``jax.lax.psum(grads, axis_name)`` inside ``shard_map``.
    ``mode`` selects the wire format (:data:`GRAD_ALLREDUCE_MODES`); ``int8``
    requires ``key`` — the per-step PRNG key that makes the stochastic
    rounding unbiased AND reproducible (thread it from the train step's rng;
    under TP, fold with the data-axis index only so model-axis replicas
    round identically). Leaf dtypes and the pytree structure are preserved.

    ``overlap`` (:data:`COMM_OVERLAP_MODES`) picks the schedule: ``off`` is
    the single-shot fused path above, byte-for-byte unchanged; ``chunked``
    cuts the flattened gradient into ``chunks`` layer-ordered pieces and
    reduces each as an independent ppermute ring
    (:func:`_ring_chunk_allreduce`, per-chunk keys ``fold_in(key, c)``) so
    XLA's latency-hiding scheduler can overlap one chunk's wire hops with
    the next chunk's quant/dequant compute.
    """
    validate_mode(mode)
    validate_overlap(overlap, chunks if overlap != "off" else None)
    if overlap == "off":
        if mode == "exact":
            return jax.lax.psum(grads, axis_name)
        if mode == "bf16":
            return jax.tree.map(
                lambda g: jax.lax.psum(g.astype(jnp.bfloat16), axis_name).astype(g.dtype),
                grads,
            )
        if key is None:
            raise ValueError("grad_allreduce mode 'int8' requires a PRNG key")
        leaves, treedef = jax.tree.flatten(grads)
        if not leaves:
            return grads
        flat = jnp.concatenate([l.astype(jnp.float32).reshape(-1) for l in leaves])
        summed = _int8_allreduce(flat, axis_name, key, bucket_size)
        out, offset = [], 0
        for l in leaves:
            out.append(summed[offset:offset + l.size].reshape(l.shape).astype(l.dtype))
            offset += l.size
        return jax.tree.unflatten(treedef, out)

    if mode == "int8" and key is None:
        raise ValueError("grad_allreduce mode 'int8' requires a PRNG key")
    leaves, treedef = jax.tree.flatten(grads)
    if not leaves:
        return grads
    if overlap == "chunked":
        flat = jnp.concatenate([l.astype(jnp.float32).reshape(-1) for l in leaves])
        pieces = []
        for c, (start, stop) in enumerate(_chunk_bounds(flat.shape[0], chunks)):
            ck = jax.random.fold_in(key, c) if key is not None else None
            pieces.append(
                _ring_chunk_allreduce(flat[start:stop], axis_name, mode, ck, bucket_size)
            )
        summed = jnp.concatenate(pieces) if len(pieces) > 1 else pieces[0]
        out, offset = [], 0
        for l in leaves:
            out.append(summed[offset:offset + l.size].reshape(l.shape).astype(l.dtype))
            offset += l.size
        return jax.tree.unflatten(treedef, out)

    # async: the chunked branch's global concatenate makes EVERY ring depend
    # on ALL cotangents, which serializes the collective tail after the full
    # backward. Here each bucket (same _chunk_bounds boundaries over the same
    # leaf-order flat layout, same fold_in(key, c) schedule — so the reduced
    # values are identical to chunked, bitwise under int8) is assembled from
    # ONLY the leaf slices it spans, and the rings are issued in reverse
    # bucket order: under reverse-mode AD the LAST layers' cotangents
    # materialize first, so the tail buckets' rings are data-ready while the
    # first layers' backward matmuls are still running — genuine
    # data-independence for XLA's latency-hiding scheduler.
    offsets, off = [], 0
    for l in leaves:
        offsets.append(off)
        off += l.size
    bounds = _chunk_bounds(off, chunks)
    reduced = [None] * len(bounds)
    for c in reversed(range(len(bounds))):
        start, stop = bounds[c]
        parts = []
        for l, loff in zip(leaves, offsets):
            lo, hi = max(start, loff), min(stop, loff + l.size)
            if lo < hi:
                parts.append(
                    l.reshape(-1)[lo - loff:hi - loff].astype(jnp.float32)
                )
        bucket = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        ck = jax.random.fold_in(key, c) if key is not None else None
        reduced[c] = _ring_chunk_allreduce(bucket, axis_name, mode, ck, bucket_size)
    out = []
    for l, loff in zip(leaves, offsets):
        pieces = []
        for (start, stop), r in zip(bounds, reduced):
            lo, hi = max(start, loff), min(stop, loff + l.size)
            if lo < hi:
                pieces.append(r[lo - start:hi - start])
        if not pieces:
            out.append(l)  # zero-size leaf: nothing was reduced
            continue
        piece = jnp.concatenate(pieces) if len(pieces) > 1 else pieces[0]
        out.append(piece.reshape(l.shape).astype(l.dtype))
    return jax.tree.unflatten(treedef, out)
