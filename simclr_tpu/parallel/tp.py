"""Tensor parallelism over the ``model`` mesh axis (projection head).

Round 1 reserved the 2-D ``(data, model)`` mesh with ``model`` unused
(SURVEY §2.3: "design mesh so a `model` axis can be added later"). This
module makes that axis real: the SimCLR projection head runs Megatron-style
tensor-parallel — ``linear1`` column-parallel, ``bn1``/relu on local
channels, ``linear2`` row-parallel with a ``psum`` over the model axis
completing the contraction (``models/heads.py:ProjectionHead``).

Design (shard_map + GSPMD hybrid):

  * **Global view for state.** Params/optimizer/checkpoints always hold the
    full (global) arrays, laid out with :func:`tp_state_shardings` — the head
    leaves sharded over ``model`` (``linear1.kernel P(None,'model')``,
    ``bn1.* P('model')``, ``linear2.kernel P('model',None)``), everything
    else replicated. Checkpoint/resume and the torch-import shim are
    untouched.
  * **Local view for compute.** Inside ``shard_map`` each shard sees its
    slice; the forward runs a local-view model (``head_hidden = hidden//tp``,
    ``head_tp_axis=MODEL_AXIS``) so Flax's parameter shape checks match the
    slices.
  * **Backward collectives via f/g boundary operators.** Under
    ``check_vma=False`` a raw forward ``psum`` transposes to ``psum``, which
    scales replicated cotangents by the axis size. The head therefore wraps
    its TP region in Megatron's f/g pair (``models/heads.py``): identity-
    forward/psum-backward at the input (completing the partial ``dL/dh``),
    psum-forward/identity-backward at the output. Gradients then leave the
    shard_map already correct — no per-leaf fixups here.
  * **Optimizer at the jit level.** ``tx.update`` runs OUTSIDE shard_map on
    the globally-sharded pytrees, so LARS trust-ratio norms are GLOBAL param
    and grad norms — XLA inserts the cross-shard reductions. Running the
    update inside shard_map would silently compute per-shard norms for the
    head and diverge from the unsharded recipe.

Equivalence is tested in tests/test_tp.py: a (d, m) mesh step matches the
(d, 1) degenerate step loss- and param-wise, and the sharded head forward
matches the unsharded module output.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from simclr_tpu.models.resnet import feature_dim
from simclr_tpu.ops.augment_pallas import validate_impl as validate_augment_impl
from simclr_tpu.ops.ntxent import (
    ntxent_loss_local_negatives,
    ntxent_loss_sharded_rows,
)
from simclr_tpu.ops.ntxent_pallas import (
    ntxent_loss_fused,
    ntxent_loss_fused_sharded,
)
from simclr_tpu.ops.ntxent_ring import ntxent_loss_ring
from simclr_tpu.parallel import compress
from simclr_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, axis_size, shard_map
from simclr_tpu.parallel.steps import (
    RESIDENCIES,
    _augment_two_views,
    _forward_fn,
    _global_sample_keys,
    _local_resident_block,
    _sharded_rows_global_batch,
)
from simclr_tpu.parallel.train_state import TrainState


def _names(path) -> list[str]:
    """Trailing DictKey names of a pytree path (works for params,
    batch_stats, and optimizer-state leaves alike — optax trace state mirrors
    the params tree under extra non-dict keys)."""
    return [str(k.key) for k in path if isinstance(k, jax.tree_util.DictKey)]


def _head_pspec(names: list[str]) -> P:
    """PartitionSpec for one leaf, by its dict-path suffix."""
    if "g" in names:
        sub = names[names.index("g"):]
        if len(sub) >= 2:
            if sub[1] == "linear1" and sub[-1] == "kernel":
                return P(None, MODEL_AXIS)  # column-parallel: out dim sharded
            if sub[1] == "linear1" and sub[-1] == "bias":
                return P(MODEL_AXIS)
            if sub[1] == "bn1":  # scale/bias params and mean/var stats
                return P(MODEL_AXIS)
            if sub[1] == "linear2" and sub[-1] == "kernel":
                return P(MODEL_AXIS, None)  # row-parallel: in dim sharded
    return P()


def tree_pspecs(tree):
    """Pytree of PartitionSpecs: head leaves sharded over ``model``."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return jax.tree_util.tree_unflatten(
        treedef, [_head_pspec(_names(path)) for path, _ in flat]
    )


def state_pspecs(state: TrainState) -> TrainState:
    """TrainState-shaped pytree of PartitionSpecs (step replicated)."""
    return TrainState(
        step=P(),
        params=tree_pspecs(state.params),
        batch_stats=tree_pspecs(state.batch_stats),
        opt_state=tree_pspecs(state.opt_state),
    )


def tp_state_shardings(mesh, state: TrainState) -> TrainState:
    """NamedSharding tree for ``jax.device_put`` of a global-view state."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        state_pspecs(state),
        is_leaf=lambda x: isinstance(x, P),
    )


def _local_view(model, tp: int):
    """The per-shard model the shard_map body applies (clone keeps every
    other field in lockstep with the global-view model)."""
    hidden = feature_dim(model.base_cnn)
    if hidden % tp:
        raise ValueError(
            f"projection hidden width {hidden} not divisible by model axis {tp}"
        )
    return model.clone(head_hidden=hidden // tp, head_tp_axis=MODEL_AXIS)


def _make_step_body(
    model,
    tx: optax.GradientTransformation,
    mesh,
    *,
    temperature: float,
    strength: float,
    out_size: int,
    negatives: str = "global",
    fused: bool = False,
    remat: bool = False,
    grad_allreduce: str = "exact",
    comm_overlap: str = "off",
    comm_chunks: int = compress.DEFAULT_COMM_CHUNKS,
    augment_impl: str = "xla",
):
    """The un-jitted TP step: shard_map'ed forward/backward + jit-level
    optimizer update. Shared by the dispatch-per-step and epoch-compiled
    paths so their numerics can never diverge (same pattern as
    ``steps._make_local_pretrain_step``). ``remat`` rematerializes the
    forward during backward exactly like ``steps._forward_fn``.

    ``grad_allreduce`` compresses the DATA-axis gradient all-reduce only
    (``parallel/compress.py``); the head's model-axis f/g collectives stay
    exact. The quantization key is forked from the data-index-folded rng, so
    model-axis replicas draw identical rounding noise and replicated
    (encoder) gradients stay identical across the model axis.
    ``comm_overlap``/``comm_chunks`` likewise apply to the data-axis ring
    only — each ppermute ring runs within a model-axis replica's data ring,
    and the gather phase forwards bytes verbatim, so model-axis replicas
    still dequantize identical gradients.

    ``negatives``/``fused`` select the NT-Xent variant with the dp path's
    exact dispatch (``steps._make_local_pretrain_step``) — the loss operates
    on the per-data-shard embeddings the TP head psum-completes, so every
    data-axis variant composes with head sharding unchanged."""
    compress.validate_mode(grad_allreduce)
    compress.validate_overlap(comm_overlap, comm_chunks)
    validate_augment_impl(augment_impl)
    if negatives not in ("global", "local", "ring"):
        raise ValueError(f"negatives must be global|local|ring, got {negatives!r}")
    if fused and negatives == "ring":
        raise ValueError(
            "loss.fused does not combine with negatives='ring' (the ring loss "
            "is already blockwise); use negatives='global' with fused"
        )
    tp = mesh.shape[MODEL_AXIS]
    local_model = _local_view(model, tp)
    fwd = _forward_fn(local_model, remat)  # the dp step's forward/remat recipe

    def local_fwd_bwd(params, batch_stats, images, rng):
        # the dp step's exact augmentation recipe (steps.py): keys are
        # global-batch-position-indexed, so model-axis replicas agree and
        # the draw survives an elastic remesh; the quant stream below stays
        # per-data-shard via the shard-folded rng
        keys = _global_sample_keys(rng, images.shape[0], views=2)
        rng = jax.random.fold_in(rng, jax.lax.axis_index(DATA_AXIS))
        v0, v1 = _augment_two_views(
            rng, images, strength, out_size, augment_impl, keys=keys
        )

        def loss_fn(p):
            z0, mut = fwd(p, batch_stats, v0)
            z1, mut = fwd(p, mut["batch_stats"], v1)
            if fused and negatives == "global":
                loss = ntxent_loss_fused_sharded(z0, z1, DATA_AXIS, temperature)
            elif fused:  # local negatives, per-shard fused kernel
                loss = jax.lax.pmean(
                    ntxent_loss_fused(z0, z1, temperature), DATA_AXIS
                )
            elif negatives == "global":
                loss = ntxent_loss_sharded_rows(z0, z1, DATA_AXIS, temperature)
            elif negatives == "ring":
                loss = ntxent_loss_ring(z0, z1, DATA_AXIS, temperature)
            else:
                loss = ntxent_loss_local_negatives(z0, z1, DATA_AXIS, temperature)
            return loss, mut["batch_stats"]

        if comm_overlap == "async":
            # staged backward (see steps._make_local_pretrain_step): explicit
            # VJP + per-bucket ring assembly in grad_allreduce lets tail
            # buckets' data-axis rings issue under earlier backward matmuls
            loss, vjp_fn, new_stats = jax.vjp(loss_fn, params, has_aux=True)
            grads, = vjp_fn(jnp.ones_like(loss))
        else:
            (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        # same convention as steps.py: sum over the data axis (compressed
        # per grad_allreduce), BEFORE the jit-level LARS update below
        grads = compress.grad_allreduce(
            grads, DATA_AXIS, grad_allreduce,
            key=jax.random.fold_in(rng, compress.KEY_FOLD_QUANT),
            overlap=comm_overlap, chunks=comm_chunks,
        )
        # No model-axis correction here: the head's f/g boundary operators
        # (models/heads.py) own the model-axis collectives in both forward
        # and backward, so encoder grads arrive complete and replica-
        # identical and head-shard grads are exact local values — pinned by
        # tests/test_tp.py::test_tp_step_matches_degenerate_model_axis.
        return loss, grads, new_stats

    def step(state: TrainState, images: jax.Array, rng: jax.Array):
        p_specs = tree_pspecs(state.params)
        s_specs = tree_pspecs(state.batch_stats)
        sharded = shard_map(
            local_fwd_bwd,
            mesh=mesh,
            in_specs=(p_specs, s_specs, P(DATA_AXIS), P()),
            out_specs=(P(), p_specs, s_specs),
            check_vma=False,
        )
        loss, grads, new_stats = sharded(state.params, state.batch_stats, images, rng)
        # jit-level (GSPMD) optimizer update: norms over the GLOBAL arrays
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=params,
            batch_stats=new_stats,
            opt_state=new_opt,
        )
        return new_state, {"loss": loss}

    return step


def make_pretrain_step_tp(
    model,
    tx: optax.GradientTransformation,
    mesh,
    *,
    temperature: float = 0.5,
    strength: float = 0.5,
    out_size: int = 32,
    negatives: str = "global",
    fused: bool = False,
    remat: bool = False,
    grad_allreduce: str = "exact",
    comm_overlap: str = "off",
    comm_chunks: int = compress.DEFAULT_COMM_CHUNKS,
    augment_impl: str = "xla",
) -> Callable[[TrainState, jax.Array, jax.Array], tuple[TrainState, dict]]:
    """Contrastive train step with the projection head tensor-parallel over
    the ``model`` mesh axis (NT-Xent negatives per ``negatives``/``fused``,
    defaulting to global rows over ``data``).

    Same contract as :func:`simclr_tpu.parallel.steps.make_pretrain_step`:
    ``(state, images_u8, rng) -> (state, metrics)``; ``state`` must be laid
    out with :func:`tp_state_shardings`. With ``model=1`` this degenerates to
    the data-parallel step (tested equivalent).
    """
    step = _make_step_body(
        model, tx, mesh,
        temperature=temperature, strength=strength, out_size=out_size,
        negatives=negatives, fused=fused,
        remat=remat, grad_allreduce=grad_allreduce,
        comm_overlap=comm_overlap, comm_chunks=comm_chunks,
        augment_impl=augment_impl,
    )
    return jax.jit(step, donate_argnums=(0,))


def make_pretrain_epoch_fn_tp(
    model,
    tx: optax.GradientTransformation,
    mesh,
    *,
    temperature: float = 0.5,
    strength: float = 0.5,
    out_size: int = 32,
    negatives: str = "global",
    fused: bool = False,
    remat: bool = False,
    residency: str = "replicated",
    grad_allreduce: str = "exact",
    comm_overlap: str = "off",
    comm_chunks: int = compress.DEFAULT_COMM_CHUNKS,
    augment_impl: str = "xla",
) -> Callable[..., tuple[TrainState, dict]]:
    """Epoch-compiled TP training: ``lax.scan`` over steps at the JIT level.

    Same contract as :func:`simclr_tpu.parallel.steps.make_pretrain_epoch_fn`
    — ``(state, images_all, idx_epoch, base_key, step0) -> (state,
    {"loss": (steps,)})`` with ``images_all`` the full uint8 dataset, placed
    per ``residency`` (replicated via ``mesh.put_replicated``, or row-sharded
    over the data axis via ``mesh.put_row_sharded``). Structure differs from
    the dp epoch fn by necessity: the dp path wraps the WHOLE scan in one
    shard_map, but the TP optimizer update must run at the jit level (LARS
    trust-ratio norms over the GLOBAL head arrays — see module docstring),
    so here the scan lives at the jit level and each body iteration
    re-enters shard_map for the forward/backward only. The per-step batch is
    gathered by index at the jit level — replicated residency takes rows
    directly and constrains to the data-axis sharding; sharded residency
    re-enters shard_map to psum-assemble each shard's slice from the row
    shards (``steps._sharded_rows_global_batch``), emerging already
    data-sharded. RNG streams (``fold_in(base_key, step0 + i)``) match the
    per-step loop exactly.
    """
    if residency not in RESIDENCIES:
        raise ValueError(
            f"residency must be one of {RESIDENCIES}, got {residency!r}"
        )
    step = _make_step_body(
        model, tx, mesh,
        temperature=temperature, strength=strength, out_size=out_size,
        negatives=negatives, fused=fused,
        remat=remat, grad_allreduce=grad_allreduce,
        comm_overlap=comm_overlap, comm_chunks=comm_chunks,
        augment_impl=augment_impl,
    )
    batched = NamedSharding(mesh, P(DATA_AXIS))

    def _local_batch_from_shards(local_rows, idx_step):
        full = _sharded_rows_global_batch(local_rows, idx_step)
        shard = jax.lax.axis_index(DATA_AXIS)
        n_local = idx_step.shape[0] // axis_size(DATA_AXIS)
        return jax.lax.dynamic_slice_in_dim(full, shard * n_local, n_local)

    gather_sharded = shard_map(
        _local_batch_from_shards,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P()),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )

    def epoch(state: TrainState, images_all, idx_epoch, base_key, step0):
        def body(state, xs):
            idx_step, i = xs
            if residency == "replicated":
                batch = jax.lax.with_sharding_constraint(
                    jnp.take(images_all, idx_step, axis=0), batched
                )
            else:
                batch = gather_sharded(images_all, idx_step)
            return step(state, batch, jax.random.fold_in(base_key, step0 + i))

        steps = idx_epoch.shape[0]
        return jax.lax.scan(
            body, state, (idx_epoch, jnp.arange(steps, dtype=jnp.int32))
        )

    return jax.jit(epoch, donate_argnums=(0,))


def make_pretrain_superepoch_fn_tp(
    model,
    tx: optax.GradientTransformation,
    mesh,
    *,
    temperature: float = 0.5,
    strength: float = 0.5,
    out_size: int = 32,
    negatives: str = "global",
    fused: bool = False,
    remat: bool = False,
    residency: str = "replicated",
    grad_allreduce: str = "exact",
    comm_overlap: str = "off",
    comm_chunks: int = compress.DEFAULT_COMM_CHUNKS,
    augment_impl: str = "xla",
    monitor=None,
) -> Callable[..., tuple[TrainState, dict]]:
    """Superepoch-compiled TP training: an outer ``lax.scan`` over K epochs
    around the :func:`make_pretrain_epoch_fn_tp` step scan, all at the JIT
    level (the TP optimizer update needs GLOBAL arrays — module docstring).

    Same calling convention as
    :func:`simclr_tpu.parallel.steps.make_pretrain_superepoch_fn`:
    ``(state, images_all, [train_labels, test_rows, test_labels,]
    idx_super, [probe_mask,] base_key, step0) -> (state, stacked metrics)``
    with ``idx_super`` the ``(K, steps, global_batch)`` index stack, RNG
    folded on absolute step indices (``step0 + k*steps + i``), and — when
    ``monitor`` is set — the in-program centroid probe gated per epoch by
    ``probe_mask``. The probe re-enters ``shard_map`` with the TP param
    specs; it only applies ``model.encode`` (encoder leaves are replicated
    under TP), so the model-sharded head leaves pass through untouched.
    """
    if residency not in RESIDENCIES:
        raise ValueError(
            f"residency must be one of {RESIDENCIES}, got {residency!r}"
        )
    step = _make_step_body(
        model, tx, mesh,
        temperature=temperature, strength=strength, out_size=out_size,
        negatives=negatives, fused=fused,
        remat=remat, grad_allreduce=grad_allreduce,
        comm_overlap=comm_overlap, comm_chunks=comm_chunks,
        augment_impl=augment_impl,
    )
    batched = NamedSharding(mesh, P(DATA_AXIS))
    array_spec = P() if residency == "replicated" else P(DATA_AXIS)

    def _local_batch_from_shards(local_rows, idx_step):
        full = _sharded_rows_global_batch(local_rows, idx_step)
        shard = jax.lax.axis_index(DATA_AXIS)
        n_local = idx_step.shape[0] // axis_size(DATA_AXIS)
        return jax.lax.dynamic_slice_in_dim(full, shard * n_local, n_local)

    gather_sharded = shard_map(
        _local_batch_from_shards,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P()),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )

    def _probe(state, images_all, train_labels, test_rows, test_labels):
        def local(params, batch_stats, imgs, tr_labels, te_rows, te_labels):
            return monitor(
                params, batch_stats,
                _local_resident_block(imgs, residency), tr_labels,
                _local_resident_block(te_rows, residency), te_labels,
            )

        sharded = shard_map(
            local,
            mesh=mesh,
            in_specs=(
                tree_pspecs(state.params), tree_pspecs(state.batch_stats),
                array_spec, P(), array_spec, P(),
            ),
            out_specs=P(),
            check_vma=False,
        )
        return sharded(
            state.params, state.batch_stats, images_all,
            train_labels, test_rows, test_labels,
        )

    def superepoch(state: TrainState, *rest):
        images_all = rest[0]
        if monitor is not None:
            train_labels, test_rows, test_labels = rest[1:4]
            idx_super, probe_mask, base_key, step0 = rest[4:]
        else:
            idx_super, base_key, step0 = rest[1:]
        steps = idx_super.shape[1]

        def step_body(state, xs):
            idx_step, i = xs
            if residency == "replicated":
                batch = jax.lax.with_sharding_constraint(
                    jnp.take(images_all, idx_step, axis=0), batched
                )
            else:
                batch = gather_sharded(images_all, idx_step)
            return step(state, batch, jax.random.fold_in(base_key, step0 + i))

        def epoch_body(state, xs):
            if monitor is not None:
                idx_epoch, k, pm = xs
            else:
                idx_epoch, k = xs
            offsets = k * steps + jnp.arange(steps, dtype=jnp.int32)
            state, hist = jax.lax.scan(step_body, state, (idx_epoch, offsets))
            if monitor is not None:
                probe = jax.lax.cond(
                    pm,
                    lambda s: _probe(
                        s, images_all, train_labels, test_rows, test_labels
                    ),
                    lambda s: {
                        name: jnp.full((), jnp.nan, jnp.float32)
                        for name in monitor.metric_names
                    },
                    state,
                )
                hist = dict(hist) | {
                    f"monitor/{name}": v for name, v in probe.items()
                }
            return state, hist

        n_epochs = idx_super.shape[0]
        epoch_ids = jnp.arange(n_epochs, dtype=jnp.int32)
        xs = (
            (idx_super, epoch_ids, probe_mask)
            if monitor is not None
            else (idx_super, epoch_ids)
        )
        return jax.lax.scan(epoch_body, state, xs)

    return jax.jit(superepoch, donate_argnums=(0,))
