"""SPMD parallelism: device mesh, shardings, and compiled train steps.

This package is the TPU-native replacement for the reference's entire
distributed runtime — the vendored process launcher
(``/root/reference/launch.py``), the NCCL process-group init
(``/root/reference/distributed_utils.py:8-24``), and the implicit DDP/SyncBN
collectives (``/root/reference/main.py:176-178``). One process per host, one
``jax.sharding.Mesh`` over all chips, and ``shard_map``-wrapped jitted steps
whose collectives (psum/pmean/all_gather) XLA schedules over ICI.
"""

from simclr_tpu.parallel.mesh import (
    MeshSpec,
    create_mesh,
    batch_sharding,
    replicated_sharding,
    mesh_from_config,
)
from simclr_tpu.parallel.train_state import TrainState

__all__ = [
    "MeshSpec",
    "create_mesh",
    "batch_sharding",
    "replicated_sharding",
    "mesh_from_config",
    "TrainState",
]
