"""Lightweight Hydra-style configuration system.

The reference drives every entry point through Hydra YAML groups plus dotted
CLI overrides (e.g. ``parameter.epochs=200``) — see
``/root/reference/main.py:134`` and ``/root/reference/conf/config.yaml``.
This module reproduces that ergonomic surface (YAML files, a ``defaults`` list
for group composition, dotted overrides with YAML-typed values, startup
validation) without the Hydra dependency, and keeps the reference's key tree
(``parameter.*``, ``experiment.*``) so recipes translate 1:1.

Differences from the reference, by design:
  * no working-directory switching — runs write to ``experiment.save_dir``
    (default ``results/<name>/seed-<seed>/<timestamp>``) without chdir;
  * the ``distributed`` group becomes ``mesh`` (a TPU device-mesh spec)
    because SPMD-with-jit replaces process-per-GPU DDP
    (``/root/reference/distributed_utils.py:8-24`` has no TPU analogue).
"""

from __future__ import annotations

import copy
import datetime
import os
from typing import Any, Iterable

import yaml

_CONF_DIR = os.path.join(os.path.dirname(__file__), "conf")


class ConfigError(ValueError):
    """Raised on malformed config files, overrides, or failed validation."""


class Config:
    """A nested, attribute-accessible configuration node.

    Behaves like a read-mostly dict-of-dicts with attribute access
    (``cfg.parameter.epochs``), mirroring OmegaConf's DictConfig surface that
    the reference code relies on.
    """

    def __init__(self, data: dict[str, Any] | None = None):
        object.__setattr__(self, "_data", {})
        for key, value in (data or {}).items():
            self._data[key] = Config(value) if isinstance(value, dict) else value

    # -- mapping protocol -------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self._data[key]

    def __setitem__(self, key: str, value: Any) -> None:
        self._data[key] = Config(value) if isinstance(value, dict) else value

    def __contains__(self, key: str) -> bool:
        return key in self._data

    def __iter__(self) -> Iterable[str]:
        return iter(self._data)

    def keys(self):
        return self._data.keys()

    def items(self):
        return self._data.items()

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    # -- attribute protocol -----------------------------------------------
    def __getattr__(self, key: str) -> Any:
        try:
            return self._data[key]
        except KeyError:
            raise AttributeError(f"config has no key {key!r}; have {list(self._data)}")

    def __setattr__(self, key: str, value: Any) -> None:
        self[key] = value

    def __repr__(self) -> str:
        return f"Config({self.to_dict()!r})"

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Config):
            return self.to_dict() == other.to_dict()
        if isinstance(other, dict):
            return self.to_dict() == other
        return NotImplemented

    # -- conversion --------------------------------------------------------
    def to_dict(self) -> dict[str, Any]:
        return {
            k: v.to_dict() if isinstance(v, Config) else copy.deepcopy(v)
            for k, v in self._data.items()
        }

    def to_yaml(self) -> str:
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    # -- dotted access -----------------------------------------------------
    def select(self, dotted: str, default: Any = None) -> Any:
        node: Any = self
        for part in dotted.split("."):
            if not isinstance(node, Config) or part not in node:
                return default
            node = node[part]
        return node

    def update_dotted(self, dotted: str, value: Any, allow_new: bool = True) -> None:
        """Set a dotted key. With ``allow_new=False`` (strict mode, used for
        CLI overrides) a path that does not already exist raises — catching
        typos like ``parameter.eopchs=5`` that would otherwise silently no-op
        (Hydra strict-mode semantics; opt into new keys with a ``+`` prefix).
        """
        parts = dotted.split(".")
        node = self
        for i, part in enumerate(parts[:-1]):
            if part in node and not isinstance(node[part], Config):
                raise ConfigError(
                    f"cannot set {dotted!r}: {'.'.join(parts[: i + 1])!r} is a "
                    f"scalar ({node[part]!r}), not a config section"
                )
            if part not in node:
                if not allow_new:
                    raise ConfigError(
                        f"override key {dotted!r} not in config (missing node "
                        f"{'.'.join(parts[: i + 1])!r}); prefix with + to add new keys"
                    )
                node[part] = Config()
            node = node[part]
        if not allow_new and parts[-1] not in node:
            raise ConfigError(
                f"override key {dotted!r} not in config; prefix with + to add new keys"
            )
        node[parts[-1]] = value


def _deep_merge(base: dict, override: dict) -> dict:
    out = dict(base)
    for key, value in override.items():
        if key in out and isinstance(out[key], dict) and isinstance(value, dict):
            out[key] = _deep_merge(out[key], value)
        else:
            out[key] = value
    return out


def _load_yaml_file(path: str) -> dict:
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    if not isinstance(data, dict):
        raise ConfigError(f"{path} must contain a mapping, got {type(data).__name__}")
    return data


def _compose(conf_dir: str, config_name: str, group_choices: dict[str, str]) -> dict:
    """Compose a root config file with its ``defaults`` group list.

    Mirrors Hydra's composition: each ``defaults`` entry ``group: option``
    loads ``<conf_dir>/<group>/<option>.yaml`` and merges it under the group
    key — unless the file opts into the root namespace with the marker key
    ``_global_: true`` (our spelling of Hydra's ``@package _global_``, which
    every reference group file uses). A group file may additionally set
    ``_override_: true`` to merge AFTER the root config (the analogue of
    placing ``_self_`` first in a Hydra defaults list), letting a recipe
    file override root-level defaults like ``parameter.linear_schedule``.
    """
    root_path = os.path.join(conf_dir, f"{config_name}.yaml")
    root = _load_yaml_file(root_path)
    defaults = root.pop("defaults", [])
    merged: dict[str, Any] = {}
    post_root: dict[str, Any] = {}
    for entry in defaults:
        if isinstance(entry, str):  # bare entry: another root-level file
            merged = _deep_merge(merged, _compose(conf_dir, entry, group_choices))
            continue
        if not isinstance(entry, dict) or len(entry) != 1:
            raise ConfigError(f"bad defaults entry {entry!r} in {root_path}")
        (group, option), = entry.items()
        option = group_choices.get(group, option)
        path = os.path.join(conf_dir, group, f"{option}.yaml")
        if not os.path.exists(path):
            raise ConfigError(
                f"config group file not found: {path} (group {group!r}, option {option!r})"
            )
        group_data = _load_yaml_file(path)
        override = group_data.pop("_override_", False)
        if not group_data.pop("_global_", False):
            group_data = {group: group_data}
        if override:
            post_root = _deep_merge(post_root, group_data)
        else:
            merged = _deep_merge(merged, group_data)
    return _deep_merge(_deep_merge(merged, root), post_root)


def _parse_override_value(raw: str) -> Any:
    # YAML 1.1 requires a dot in floats, so safe_load('1e-4') is the STRING
    # '1e-4' — but reference recipes write decay=1e-4. Try numeric forms
    # first, then fall back to YAML typing (bools, null, lists, strings).
    stripped = raw.strip()
    try:
        return int(stripped)
    except ValueError:
        pass
    try:
        return float(stripped)
    except ValueError:
        pass
    try:
        return yaml.safe_load(raw)
    except yaml.YAMLError:
        return raw


def parse_overrides(
    argv: list[str], conf_dir: str | None = None
) -> tuple[dict[str, str], list[tuple[str, Any]]]:
    """Split ``group=option`` choices from ``a.b.c=value`` dotted overrides.

    A bare key (no dot) whose name matches a config group directory under
    ``conf_dir`` selects a group option, exactly like Hydra's
    ``experiment=cifar10``; everything else is a typed value override.
    """
    conf_dir = conf_dir or _CONF_DIR
    group_choices: dict[str, str] = {}
    value_overrides: list[tuple[str, Any]] = []
    for arg in argv:
        if "=" not in arg:
            raise ConfigError(
                f"override {arg!r} must look like key=value (e.g. parameter.epochs=200)"
            )
        key, raw = arg.split("=", 1)
        key = key.strip()
        if "." not in key and os.path.isdir(os.path.join(conf_dir, key.lstrip("+"))):
            group_choices[key.lstrip("+")] = raw.strip()
        else:
            value_overrides.append((key, _parse_override_value(raw)))
    return group_choices, value_overrides


def load_config(
    config_name: str,
    overrides: list[str] | None = None,
    conf_dir: str | None = None,
) -> Config:
    """Load ``<conf_dir>/<config_name>.yaml``, compose groups, apply overrides."""
    conf_dir = conf_dir or _CONF_DIR
    group_choices, value_overrides = parse_overrides(list(overrides or []), conf_dir)
    cfg = Config(_compose(conf_dir, config_name, group_choices))
    for dotted, value in value_overrides:
        if dotted.startswith("+"):
            cfg.update_dotted(dotted[1:], value, allow_new=True)
        else:
            cfg.update_dotted(dotted, value, allow_new=False)
    return cfg


def resolve_save_dir(cfg: Config, now: datetime.datetime | None = None) -> str:
    """Compute the run output directory.

    The reference relies on Hydra's auto-chdir into
    ``results/${experiment.name}/seed-${parameter.seed}/<date>/<time>``
    (``/root/reference/conf/hydra/output/custom.yaml:2-8``). We compute the
    same path but never chdir; callers pass it explicitly.
    """
    explicit = cfg.select("experiment.save_dir")
    if explicit:
        return str(explicit)
    now = now or datetime.datetime.now()
    return os.path.join(
        "results",
        str(cfg.experiment.name),
        f"seed-{cfg.parameter.seed}",
        now.strftime("%Y-%m-%d"),
        now.strftime("%H-%M-%S"),
    )


# ---------------------------------------------------------------------------
# Multirun sweeps — the reference's Hydra sweep surface
# (``/root/reference/conf/hydra/output/custom.yaml:6-8``: ``hydra.sweep.dir``
# + job-number subdirs). ``--multirun`` / ``-m`` on any entry point expands
# comma-list overrides into the cartesian product of jobs, each writing to
# ``<sweep_root>/<job_idx>``.
# ---------------------------------------------------------------------------

MULTIRUN_FLAGS = ("--multirun", "-m")


def split_multirun_flag(argv: list[str]) -> tuple[bool, list[str]]:
    """Strip Hydra's multirun flag from an argv-style override list."""
    multirun = any(a in MULTIRUN_FLAGS for a in argv)
    return multirun, [a for a in argv if a not in MULTIRUN_FLAGS]


def expand_sweep(argv: list[str]) -> list[list[str]]:
    """Expand ``key=v1,v2`` comma-list overrides into single-run combos.

    Mirrors Hydra's multirun semantics: every comma-listed override
    contributes one axis, and jobs are the cartesian product in argv order.
    A bracketed value (``key=[a,b]``) is one YAML list, not a sweep axis,
    and so is a quoted value (``key="a, b"`` — the shell strips nothing
    inside the quotes, so the comma is literal).

    ``experiment.save_dir`` may not be swept: :func:`run_multirun` overwrites
    every job's save_dir with ``<sweep_root>/<job_idx>``, so swept values
    would be silently discarded — rejected here instead.
    """
    import itertools

    axes: list[list[str]] = []
    for arg in argv:
        if "=" not in arg:
            raise ConfigError(
                f"override {arg!r} must look like key=value (e.g. parameter.epochs=200)"
            )
        key, raw = arg.split("=", 1)
        stripped = raw.strip()
        quoted = (
            len(stripped) >= 2
            and stripped[0] in "'\""
            and stripped[-1] == stripped[0]
        )
        if "," in stripped and not quoted and not stripped.startswith("["):
            if key.strip().lstrip("+") == "experiment.save_dir":
                raise ConfigError(
                    f"experiment.save_dir cannot be a sweep axis ({arg!r}): "
                    "multirun assigns each job <sweep_root>/<job_idx> and "
                    "would silently ignore the swept values; set a single "
                    "experiment.save_dir as the sweep root instead"
                )
            values = [v.strip() for v in stripped.split(",")]
            if any(not v for v in values):
                raise ConfigError(f"empty value in sweep override {arg!r}")
            axes.append([f"{key}={v}" for v in values])
        else:
            axes.append([arg])
    return [list(combo) for combo in itertools.product(*axes)]


def run_multirun(run_fn, config_name: str, argv: list[str]) -> list:
    """Run ``run_fn(cfg)`` once per sweep job, sequentially.

    Every job writes under one sweep root in a ``<job_idx>`` subdir, the
    analogue of Hydra's ``hydra.sweep.dir``/``subdir`` layout. The root is
    an explicit ``experiment.save_dir`` when given; otherwise a NEUTRAL
    dated ``results/multirun/...`` dir — job 0's own resolved save dir
    would encode job 0's name/seed in the path and misattribute the other
    jobs' results (e.g. a ``parameter.seed=3,5`` sweep filing seed-5 under
    ``seed-3/``). Returns the per-job results in job order.
    """
    import logging

    combos = expand_sweep(argv)
    sweep_root: str | None = None
    results = []
    for i, combo in enumerate(combos):
        cfg = load_config(config_name, overrides=combo)
        if sweep_root is None:
            explicit = cfg.select("experiment.save_dir")
            if explicit:
                sweep_root = str(explicit)
            else:
                import jax

                if jax.process_count() > 1:
                    raise ConfigError(
                        "multirun without an explicit experiment.save_dir is "
                        "not multi-process safe: each process would compute "
                        "its own dated sweep root and the ranks would "
                        "desynchronize; set experiment.save_dir to a shared "
                        "directory"
                    )
                now = datetime.datetime.now()
                sweep_root = os.path.join(
                    "results", "multirun",
                    now.strftime("%Y-%m-%d"), now.strftime("%H-%M-%S"),
                )
        cfg.update_dotted(
            "experiment.save_dir", os.path.join(sweep_root, str(i)), allow_new=True
        )
        logging.getLogger("simclr_tpu").info(
            "multirun job %d/%d: %s -> %s", i + 1, len(combos),
            " ".join(combo) or "<defaults>", cfg.experiment.save_dir,
        )
        results.append(run_fn(cfg))
    return results


# ---------------------------------------------------------------------------
# Startup validation — the reference's hand-rolled asserts, kept as explicit
# contracts (main.py:39-50, eval.py:20-28, supervised.py:18-27,
# save_features.py:15-17 in /root/reference).
# ---------------------------------------------------------------------------

def _require(cond: bool, message: str) -> None:
    if not cond:
        raise ConfigError(message)


def _supported_cnns() -> tuple[str, ...]:
    """Architectures from the single-source zoo table (models/arch.py)."""
    from simclr_tpu.models.arch import STAGE_SIZES

    return tuple(sorted(STAGE_SIZES))


def check_pretrain_conf(cfg: Config) -> None:
    p = cfg.parameter
    _require(p.epochs > 0, "parameter.epochs must be positive")
    _require(0 < p.temperature, "parameter.temperature must be positive")
    _require(p.d > 0, "parameter.d (projection dim) must be positive")
    _require(p.warmup_epochs >= 0, "parameter.warmup_epochs must be >= 0")
    _require(p.warmup_epochs <= p.epochs, "warmup_epochs must be <= epochs")
    _require(0.0 <= p.momentum <= 1.0, "parameter.momentum must be in [0, 1]")
    e = cfg.experiment
    _require(e.batches > 0, "experiment.batches (per-device batch) must be positive")
    _require(e.lr > 0, "experiment.lr must be positive")
    _require(e.decay >= 0, "experiment.decay must be >= 0")
    _require(0.0 <= e.strength <= 1.0, "experiment.strength must be in [0, 1]")
    _require(
        e.base_cnn in _supported_cnns(),
        f"experiment.base_cnn must be {'|'.join(_supported_cnns())}, "
        f"got {e.base_cnn!r}",
    )
    _require(
        e.name in ("cifar10", "cifar100"),
        f"experiment.name must be cifar10|cifar100, got {e.name!r}",
    )
    _require(
        cfg.select("loss.negatives", "global") in ("global", "local", "ring"),
        "loss.negatives must be 'global', 'local', or 'ring'",
    )
    _check_runtime_conf(cfg)


def _check_runtime_conf(cfg: Config) -> None:
    _require(
        cfg.select("runtime.dataset_residency", "replicated")
        in ("replicated", "sharded"),
        "runtime.dataset_residency must be 'replicated' or 'sharded'",
    )
    # the one true universe lives in ops/augment_pallas.AUGMENT_IMPLS; the
    # import is lazy so merely validating a config stays jax-free
    impl = cfg.select("runtime.augment_impl", "xla")
    from simclr_tpu.ops.augment_pallas import AUGMENT_IMPLS

    _require(
        impl in AUGMENT_IMPLS,
        f"runtime.augment_impl must be {'|'.join(AUGMENT_IMPLS)}, "
        f"got {impl!r}",
    )
    k = cfg.select("runtime.epochs_per_compile", 1)
    _require(
        isinstance(k, int) and not isinstance(k, bool) and k >= 1,
        f"runtime.epochs_per_compile must be an int >= 1, got {k!r}",
    )
    _require(
        k == 1 or bool(cfg.select("runtime.epoch_compile", False)),
        "runtime.epochs_per_compile > 1 (superepochs) requires "
        "runtime.epoch_compile=true — the superepoch scan is the epoch "
        "scan's outer loop",
    )
    _check_parallel_conf(cfg)
    _check_supervisor_conf(cfg)
    _check_telemetry_conf(cfg)


def check_telemetry_conf(cfg: Config) -> None:
    """Validate the ``telemetry.*`` knobs (run observability,
    docs/OBSERVABILITY.md). Called by both training entry points via
    :func:`_check_runtime_conf` and by the supervisor runner — like the
    supervisor knobs, a bad value fails at startup on either side of the
    process boundary. Deliberately jax-free."""
    _check_telemetry_conf(cfg)


def _check_telemetry_conf(cfg: Config) -> None:
    port = cfg.select("telemetry.port", 0)
    _require(
        isinstance(port, int) and not isinstance(port, bool)
        and 0 <= port <= 65535,
        f"telemetry.port must be an int in [0, 65535] (0 = exporter "
        f"disabled unless telemetry.ready_file is set), got {port!r}",
    )
    trace_max_ms = cfg.select("telemetry.trace_max_ms", 60000)
    _require(
        isinstance(trace_max_ms, (int, float)) and not isinstance(trace_max_ms, bool)
        and 0 < trace_max_ms <= 600000,
        "telemetry.trace_max_ms must be in (0, 600000] milliseconds "
        f"(cap for POST /debug/trace?ms=N), got {trace_max_ms!r}",
    )
    events = cfg.select("telemetry.events", True)
    _require(
        isinstance(events, bool),
        f"telemetry.events must be a boolean (true|false), got {events!r}",
    )
    anomaly = cfg.select("telemetry.anomaly", True)
    _require(
        isinstance(anomaly, bool),
        f"telemetry.anomaly must be a boolean (true|false), got {anomaly!r}",
    )
    warmup = cfg.select("telemetry.anomaly_warmup", 8)
    _require(
        isinstance(warmup, int) and not isinstance(warmup, bool)
        and 2 <= warmup <= 10000,
        "telemetry.anomaly_warmup must be an int in [2, 10000] step samples "
        f"before the detector classifies anything, got {warmup!r}",
    )
    slow_factor = cfg.select("telemetry.slow_step_factor", 4.0)
    _require(
        isinstance(slow_factor, (int, float)) and not isinstance(slow_factor, bool)
        and 1 <= slow_factor <= 1000,
        "telemetry.slow_step_factor must be in [1, 1000] MAD multiples over "
        f"the rolling median, got {slow_factor!r}",
    )
    stall_factor = cfg.select("telemetry.stall_factor", 10.0)
    _require(
        isinstance(stall_factor, (int, float)) and not isinstance(stall_factor, bool)
        and 1 <= stall_factor <= 1000,
        "telemetry.stall_factor must be in [1, 1000] multiples of the median "
        f"step time (stall watchdog deadline), got {stall_factor!r}",
    )
    stall_min = cfg.select("telemetry.stall_min_s", 2.0)
    _require(
        isinstance(stall_min, (int, float)) and not isinstance(stall_min, bool)
        and 0 < stall_min <= 3600,
        "telemetry.stall_min_s must be in (0, 3600] seconds (floor on the "
        f"stall watchdog deadline), got {stall_min!r}",
    )
    auto_trace = cfg.select("telemetry.auto_trace", False)
    _require(
        isinstance(auto_trace, bool),
        f"telemetry.auto_trace must be a boolean (true|false), got {auto_trace!r}",
    )
    auto_trace_ms = cfg.select("telemetry.auto_trace_ms", 500)
    _require(
        isinstance(auto_trace_ms, (int, float))
        and not isinstance(auto_trace_ms, bool)
        and 0 < auto_trace_ms <= 60000,
        "telemetry.auto_trace_ms must be in (0, 60000] milliseconds per "
        f"automatic capture, got {auto_trace_ms!r}",
    )
    cooldown = cfg.select("telemetry.auto_trace_cooldown_s", 300.0)
    _require(
        isinstance(cooldown, (int, float)) and not isinstance(cooldown, bool)
        and 0 <= cooldown <= 86400,
        "telemetry.auto_trace_cooldown_s must be in [0, 86400] seconds "
        f"between automatic captures, got {cooldown!r}",
    )
    auto_trace_max = cfg.select("telemetry.auto_trace_max", 3)
    _require(
        isinstance(auto_trace_max, int) and not isinstance(auto_trace_max, bool)
        and 1 <= auto_trace_max <= 100,
        "telemetry.auto_trace_max must be an int in [1, 100] automatic "
        f"captures per attempt, got {auto_trace_max!r}",
    )
    compile_sentry = cfg.select("telemetry.compile_sentry", True)
    _require(
        isinstance(compile_sentry, bool),
        f"telemetry.compile_sentry must be a boolean (true|false), "
        f"got {compile_sentry!r}",
    )
    hbm = cfg.select("telemetry.hbm", True)
    _require(
        isinstance(hbm, bool),
        f"telemetry.hbm must be a boolean (true|false), got {hbm!r}",
    )
    fleet = cfg.select("telemetry.fleet", False)
    _require(
        isinstance(fleet, bool),
        f"telemetry.fleet must be a boolean (true|false), got {fleet!r}",
    )
    fleet_port = cfg.select("telemetry.fleet_port", 0)
    _require(
        isinstance(fleet_port, int) and not isinstance(fleet_port, bool)
        and 0 <= fleet_port <= 65535,
        "telemetry.fleet_port must be an int in [0, 65535] (0 = ephemeral, "
        f"published via the fleet ready file), got {fleet_port!r}",
    )
    fleet_poll = cfg.select("telemetry.fleet_poll_s", 2.0)
    _require(
        isinstance(fleet_poll, (int, float)) and not isinstance(fleet_poll, bool)
        and 0 < fleet_poll <= 3600,
        "telemetry.fleet_poll_s must be in (0, 3600] seconds between fleet "
        f"scrape passes, got {fleet_poll!r}",
    )
    fleet_stale = cfg.select("telemetry.fleet_stale_after_s", 30.0)
    _require(
        isinstance(fleet_stale, (int, float)) and not isinstance(fleet_stale, bool)
        and 0 < fleet_stale <= 86400,
        "telemetry.fleet_stale_after_s must be in (0, 86400] seconds before "
        f"a silent host is gauged stale, got {fleet_stale!r}",
    )


def check_supervisor_conf(cfg: Config) -> None:
    """Validate the ``supervisor.*`` knobs (fault-tolerance policy,
    docs/FAULT_TOLERANCE.md). Called by the supervisor runner before it
    spawns anything, and by both training entry points via
    :func:`_check_runtime_conf` — a bad knob fails at startup on either side
    of the process boundary. Deliberately jax-free: the runner validates
    without touching any accelerator state."""
    _check_supervisor_conf(cfg)


def _check_supervisor_conf(cfg: Config) -> None:
    max_restarts = cfg.select("supervisor.max_restarts", 8)
    _require(
        isinstance(max_restarts, int) and 0 <= max_restarts <= 1000,
        f"supervisor.max_restarts must be an int in [0, 1000], got {max_restarts!r}",
    )
    backoff = cfg.select("supervisor.backoff_base_s", 5.0)
    _require(
        isinstance(backoff, (int, float)) and 0 <= backoff <= 3600,
        f"supervisor.backoff_base_s must be in [0, 3600] seconds, got {backoff!r}",
    )
    backoff_max = cfg.select("supervisor.backoff_max_s", 300.0)
    _require(
        isinstance(backoff_max, (int, float)) and 0 <= backoff_max <= 86400,
        f"supervisor.backoff_max_s must be in [0, 86400] seconds, "
        f"got {backoff_max!r}",
    )
    _require(
        backoff_max >= backoff,
        f"supervisor.backoff_max_s ({backoff_max!r}) must be >= "
        f"supervisor.backoff_base_s ({backoff!r}) — a cap below the base "
        "delay would make every restart wait the cap",
    )
    grow_back = cfg.select("supervisor.grow_back_cooldown_s", 60.0)
    _require(
        isinstance(grow_back, (int, float)) and 0 <= grow_back <= 86400,
        f"supervisor.grow_back_cooldown_s must be in [0, 86400] seconds, "
        f"got {grow_back!r}",
    )
    factor = cfg.select("supervisor.heartbeat_timeout_factor", 10.0)
    _require(
        isinstance(factor, (int, float)) and 1 <= factor <= 1000,
        "supervisor.heartbeat_timeout_factor must be in [1, 1000] "
        f"(multiples of the observed step time), got {factor!r}",
    )
    min_timeout = cfg.select("supervisor.heartbeat_min_timeout_s", 30.0)
    _require(
        isinstance(min_timeout, (int, float)) and 0 < min_timeout <= 86400,
        "supervisor.heartbeat_min_timeout_s must be in (0, 86400] seconds, "
        f"got {min_timeout!r}",
    )
    grace = cfg.select("supervisor.startup_grace_s", 600.0)
    _require(
        isinstance(grace, (int, float)) and 0 < grace <= 86400,
        f"supervisor.startup_grace_s must be in (0, 86400] seconds, got {grace!r}",
    )
    nan_budget = cfg.select("supervisor.nan_retry_budget", 2)
    _require(
        isinstance(nan_budget, int) and 0 <= nan_budget <= 100,
        f"supervisor.nan_retry_budget must be an int in [0, 100], got {nan_budget!r}",
    )


def _check_parallel_conf(cfg: Config) -> None:
    # single source of truth for the valid sets/ranges: parallel/compress.py
    from simclr_tpu.parallel.compress import (
        COMM_OVERLAP_MODES,
        DEFAULT_COMM_CHUNKS,
        GRAD_ALLREDUCE_MODES,
        MAX_COMM_CHUNKS,
        normalize_overlap,
    )

    mode = cfg.select("parallel.grad_allreduce", "exact")
    _require(
        mode in GRAD_ALLREDUCE_MODES,
        f"parallel.grad_allreduce must be one of {GRAD_ALLREDUCE_MODES}, "
        f"got {mode!r}",
    )
    overlap = normalize_overlap(cfg.select("parallel.comm_overlap", "off"))
    _require(
        overlap in COMM_OVERLAP_MODES,
        f"parallel.comm_overlap must be one of {COMM_OVERLAP_MODES}, "
        f"got {overlap!r}",
    )
    chunks = cfg.select("parallel.comm_chunks", DEFAULT_COMM_CHUNKS)
    _require(
        isinstance(chunks, int) and not isinstance(chunks, bool)
        and 1 <= chunks <= MAX_COMM_CHUNKS,
        f"parallel.comm_chunks must be an int in [1, {MAX_COMM_CHUNKS}], "
        f"got {chunks!r}",
    )


def check_eval_conf(cfg: Config) -> None:
    p = cfg.parameter
    _require(p.epochs >= 0, "parameter.epochs must be >= 0")
    _require(p.top_k > 0, "parameter.top_k must be positive")
    _require(
        p.classifier in ("centroid", "linear", "nonlinear"),
        f"parameter.classifier must be centroid|linear|nonlinear, got {p.classifier!r}",
    )
    _require(bool(cfg.experiment.target_dir), "experiment.target_dir must be set")
    _require(cfg.experiment.target_dir != "DUMMY-PATH", "experiment.target_dir must be set")


def check_supervised_conf(cfg: Config) -> None:
    p = cfg.parameter
    _require(p.epochs > 0, "parameter.epochs must be positive")
    _require(p.metric in ("loss", "acc"), "parameter.metric must be loss|acc")
    _require(p.warmup_epochs >= 0, "parameter.warmup_epochs must be >= 0")
    _check_runtime_conf(cfg)


def check_save_features_conf(cfg: Config) -> None:
    _require(bool(cfg.experiment.target_dir), "experiment.target_dir must be set")
    _require(cfg.experiment.target_dir != "DUMMY-PATH", "experiment.target_dir must be set")


def check_serve_conf(
    cfg: Config, *, require_checkpoint_source: bool = True
) -> None:
    s = cfg.select("serve")
    _require(s is not None, "serve config group missing (load_config('serve'))")
    _require(int(s.max_batch) > 0, "serve.max_batch must be positive")
    _require(float(s.max_delay_ms) >= 0, "serve.max_delay_ms must be >= 0")
    _require(int(s.queue_depth) > 0, "serve.queue_depth must be positive")
    _require(float(s.request_timeout_s) > 0, "serve.request_timeout_s must be positive")
    _require(0 <= int(s.port) <= 65535, "serve.port must be in [0, 65535]")
    rate = cfg.select("serve.trace_sample_rate", 0.0)
    _require(
        isinstance(rate, (int, float)) and not isinstance(rate, bool)
        and 0.0 <= rate <= 1.0,
        "serve.trace_sample_rate must be in [0.0, 1.0] (fraction of request "
        f"traces sampled into serve.requests_log), got {rate!r}",
    )
    requests_log = cfg.select("serve.requests_log")
    _require(
        requests_log is None or isinstance(requests_log, str),
        "serve.requests_log must be a path string or null (null = no "
        f"sidecar), got {requests_log!r}",
    )
    replicas = cfg.select("serve.replicas", -1)
    _require(
        isinstance(replicas, int) and not isinstance(replicas, bool)
        and (replicas == -1 or replicas >= 1),
        "serve.replicas must be -1 (one replica per local device) or a "
        f"positive int, got {replicas!r}",
    )
    # mirrors parallel.compress.WEIGHT_QUANT_MODES; inlined because this
    # module is deliberately jax-free
    weights = cfg.select("serve.weights", "exact")
    _require(
        weights in ("exact", "bf16", "int8"),
        f"serve.weights must be exact|bf16|int8, got {weights!r}",
    )
    corpus = cfg.select("serve.corpus")
    _require(
        corpus is None or isinstance(corpus, str),
        "serve.corpus must be an (n, d) .npy/.npz path or null (null = "
        f"no /v1/neighbors), got {corpus!r}",
    )
    k = cfg.select("serve.neighbors_k", 10)
    _require(
        isinstance(k, int) and not isinstance(k, bool) and k >= 1,
        f"serve.neighbors_k must be an int >= 1, got {k!r}",
    )
    metric = cfg.select("serve.neighbors_metric", "dot")
    _require(
        metric in ("dot", "cosine"),
        f"serve.neighbors_metric must be dot|cosine, got {metric!r}",
    )
    # mirrors parallel.compress.CORPUS_DTYPE_MODES (jax-free module, same
    # reason serve.weights inlines its set)
    corpus_dtype = cfg.select("serve.corpus_dtype", "fp32")
    _require(
        corpus_dtype in ("fp32", "int8"),
        f"serve.corpus_dtype must be fp32|int8, got {corpus_dtype!r}",
    )
    ann_cells = cfg.select("serve.ann_cells", 0)
    _require(
        isinstance(ann_cells, int) and not isinstance(ann_cells, bool)
        and 0 <= ann_cells <= 65536,
        "serve.ann_cells must be an int in [0, 65536] (IVF cells per shard; "
        f"0 = exact scan), got {ann_cells!r}",
    )
    ann_probe = cfg.select("serve.ann_probe", 1)
    _require(
        isinstance(ann_probe, int) and not isinstance(ann_probe, bool)
        and ann_probe >= 1,
        f"serve.ann_probe must be an int >= 1 (cells scored per query), "
        f"got {ann_probe!r}",
    )
    _require(
        ann_cells == 0 or ann_probe <= ann_cells,
        f"serve.ann_probe must be <= serve.ann_cells ({ann_cells}) when the "
        f"IVF scan is on, got {ann_probe!r}",
    )
    # one of the checkpoint sources must be real — except under the
    # co-scheduler, which serves random generation-0 weights and hot-reloads
    # checkpoints as training writes them (check_cosched_conf)
    if require_checkpoint_source and not s.get("checkpoint"):
        _require(
            bool(cfg.experiment.target_dir)
            and cfg.experiment.target_dir != "DUMMY-PATH",
            "set experiment.target_dir (checkpoint run dir) or serve.checkpoint",
        )


def check_cosched_conf(cfg: Config) -> None:
    """Validate the co-scheduler surface (``cosched.*`` plus the serve,
    supervisor, and telemetry knobs it composes — ``conf/cosched.yaml``).
    The serve tier starts on random generation-0 weights and hot-reloads
    each checkpoint the training run writes, so unlike the standalone
    server no pre-existing checkpoint source is required."""
    check_serve_conf(cfg, require_checkpoint_source=False)
    _check_supervisor_conf(cfg)
    _check_telemetry_conf(cfg)
    c = cfg.select("cosched")
    _require(c is not None, "cosched config group missing (load_config('cosched'))")
    serve_devices = cfg.select("cosched.serve_devices", 1)
    _require(
        isinstance(serve_devices, int) and not isinstance(serve_devices, bool)
        and serve_devices >= 1,
        "cosched.serve_devices must be an int >= 1 (local devices reserved "
        f"for the serve tier), got {serve_devices!r}",
    )
    max_serve = cfg.select("cosched.max_serve_devices", serve_devices)
    _require(
        isinstance(max_serve, int) and not isinstance(max_serve, bool)
        and max_serve >= serve_devices,
        "cosched.max_serve_devices must be an int >= cosched.serve_devices "
        f"(ceiling the elastic grow can reach), got {max_serve!r}",
    )
    poll = cfg.select("cosched.reload_poll_s", 2.0)
    _require(
        isinstance(poll, (int, float)) and not isinstance(poll, bool)
        and 0 < poll <= 3600,
        f"cosched.reload_poll_s must be in (0, 3600] seconds between "
        f"checkpoint-watch passes, got {poll!r}",
    )
    corpus_images = cfg.select("cosched.corpus_images", 0)
    _require(
        isinstance(corpus_images, int) and not isinstance(corpus_images, bool)
        and 0 <= corpus_images <= 1_000_000,
        "cosched.corpus_images must be an int in [0, 1000000] retrieval "
        f"corpus rows (0 = no /v1/neighbors), got {corpus_images!r}",
    )
    reembed = cfg.select("cosched.reembed_batch", 256)
    _require(
        isinstance(reembed, int) and not isinstance(reembed, bool)
        and 1 <= reembed <= 4096,
        f"cosched.reembed_batch must be an int in [1, 4096] rows per "
        f"re-embed forward, got {reembed!r}",
    )
    realloc = cfg.select("cosched.reallocation", True)
    _require(
        isinstance(realloc, bool),
        f"cosched.reallocation must be a boolean (true|false), got {realloc!r}",
    )
    high = cfg.select("cosched.pressure_high", 0.75)
    low = cfg.select("cosched.pressure_low", 0.1)
    for name, v in (("pressure_high", high), ("pressure_low", low)):
        _require(
            isinstance(v, (int, float)) and not isinstance(v, bool)
            and 0.0 <= v <= 1.0,
            f"cosched.{name} must be in [0.0, 1.0] (fraction of "
            f"serve.queue_depth), got {v!r}",
        )
    _require(
        low < high,
        f"cosched.pressure_low ({low!r}) must be < cosched.pressure_high "
        f"({high!r}) — the hysteresis band cannot be empty",
    )
    sustain = cfg.select("cosched.pressure_sustain_s", 10.0)
    _require(
        isinstance(sustain, (int, float)) and not isinstance(sustain, bool)
        and 0 <= sustain <= 3600,
        "cosched.pressure_sustain_s must be in [0, 3600] seconds of "
        f"sustained pressure before reallocating, got {sustain!r}",
    )
    cooldown = cfg.select("cosched.realloc_cooldown_s", 30.0)
    _require(
        isinstance(cooldown, (int, float)) and not isinstance(cooldown, bool)
        and 0 <= cooldown <= 86400,
        "cosched.realloc_cooldown_s must be in [0, 86400] seconds between "
        f"reallocation direction changes, got {cooldown!r}",
    )
