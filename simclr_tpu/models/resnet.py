"""TPU-native ResNet encoders (Flax linen, NHWC, bfloat16 compute).

Provides the backbone capability of the reference's torchvision ResNet-18/50
(plus ResNet-34, an addition beyond its zoo)
with CIFAR stem surgery (``/root/reference/model.py:97-111``): a 3x3 stride-1
stem conv, no stem max-pool, and the classification ``fc`` dropped so the
encoder emits pooled features ``h``.

Design notes (TPU-first, not a torch translation):
  * NHWC layout and bfloat16 compute (`dtype`) with float32 params and BN
    statistics — convs land on the MXU, BN stays numerically safe.
  * BatchNorm is *plain* batch-mean normalization: under ``jit`` over a
    sharded batch axis, XLA turns the batch reduction into a cross-replica
    collective automatically, which IS the reference's SyncBN
    (``torch.nn.SyncBatchNorm.convert_sync_batchnorm``,
    ``/root/reference/main.py:176``) without a dedicated engine. When run
    under ``shard_map`` instead, pass ``bn_cross_replica_axis`` so BN pmeans
    its statistics over the data axis explicitly.
  * Static shapes and Python-level (trace-time) architecture selection only —
    no data-dependent control flow, so XLA can fuse and tile freely.

Deviations from the reference, documented:
  * The reference's CIFAR stem uses ``padding=3`` on a 3x3 conv
    (``/root/reference/model.py:99-101``), an apparent typo inherited from the
    7x7 stem that inflates 32x32 inputs to 36x36 maps. We use SAME padding,
    matching the SimCLR paper's CIFAR variant.
  * The reference only applies CIFAR surgery to resnet18
    (``/root/reference/model.py:90-104``); we apply it to every depth when
    ``cifar_stem=True`` since that is the documented intent.
"""

from __future__ import annotations

from collections.abc import Callable
from functools import partial
from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from simclr_tpu.models.arch import (  # single source of truth for the zoo
    BASIC_BLOCK_CNNS as _BASIC_BLOCK_CNNS,
    FEATURE_DIMS,
    STAGE_SIZES as _STAGE_SIZES,
    STAGE_WIDTHS as _STAGE_WIDTHS,
)

Dtype = Any

# torch resnets init convs with kaiming_normal(fan_out, relu); reproduce so
# training dynamics match the reference recipe.
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "truncated_normal")


# BatchNorm pinned to torch hyperparameters (eps 1e-5, running-stat momentum
# 0.1 → flax momentum 0.9). `axis_name` is only needed under shard_map/pmap;
# under plain GSPMD jit the batch reduction is already global (= SyncBN).
BatchNorm = partial(nn.BatchNorm, momentum=0.9, epsilon=1e-5, param_dtype=jnp.float32)


class BasicBlock(nn.Module):
    """Two 3x3 convs + identity/projection shortcut (ResNet-18/34 block)."""

    filters: int
    strides: int = 1
    norm: Callable[..., nn.Module] = BatchNorm
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            kernel_init=conv_kernel_init,
        )
        norm = partial(self.norm, use_running_average=not train, dtype=self.dtype)

        residual = x
        # explicit symmetric padding: XLA's SAME pads (0,1) at stride 2,
        # which would misalign weights imported from torch checkpoints
        # (utils/torch_import.py); (1,1) matches torch conv padding=1
        y = conv(
            self.filters, (3, 3), strides=(self.strides, self.strides),
            padding=((1, 1), (1, 1)),
        )(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), padding=((1, 1), (1, 1)))(y)
        y = norm()(y)

        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), strides=(self.strides, self.strides))(
                residual
            )
            residual = norm()(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1(x4) bottleneck (ResNet-50 block, expansion 4)."""

    filters: int
    strides: int = 1
    norm: Callable[..., nn.Module] = BatchNorm
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            kernel_init=conv_kernel_init,
        )
        norm = partial(self.norm, use_running_average=not train, dtype=self.dtype)

        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(
            self.filters, (3, 3), strides=(self.strides, self.strides),
            padding=((1, 1), (1, 1)),  # torch-aligned (see BasicBlock)
        )(y)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm()(y)

        if residual.shape != y.shape:
            residual = conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNetEncoder(nn.Module):
    """ResNet v1 feature encoder: images (N,H,W,3) -> pooled features (N,D).

    Equivalent to the reference's ``self.f`` with ``fc`` replaced by identity
    (``/root/reference/model.py:111``): stem -> 4 stages -> global average
    pool. ``cifar_stem`` selects the 3x3/stride-1/no-maxpool stem.
    """

    base_cnn: str = "resnet18"
    cifar_stem: bool = True
    dtype: Dtype = jnp.bfloat16
    bn_cross_replica_axis: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.base_cnn not in _STAGE_SIZES:
            raise ValueError(
                f"base_cnn must be one of {sorted(_STAGE_SIZES)}, got {self.base_cnn!r}"
            )
        stage_sizes = _STAGE_SIZES[self.base_cnn]
        block_cls = (
            BasicBlock if self.base_cnn in _BASIC_BLOCK_CNNS else BottleneckBlock
        )
        norm = partial(BatchNorm, axis_name=self.bn_cross_replica_axis)

        x = x.astype(self.dtype)
        if self.cifar_stem:
            x = nn.Conv(
                64,
                (3, 3),
                strides=(1, 1),
                padding=((1, 1), (1, 1)),
                use_bias=False,
                dtype=self.dtype,
                param_dtype=jnp.float32,
                kernel_init=conv_kernel_init,
                name="stem_conv",
            )(x)
            x = norm(use_running_average=not train, dtype=self.dtype)(x)
            x = nn.relu(x)
        else:
            x = nn.Conv(
                64,
                (7, 7),
                strides=(2, 2),
                padding=((3, 3), (3, 3)),
                use_bias=False,
                dtype=self.dtype,
                param_dtype=jnp.float32,
                kernel_init=conv_kernel_init,
                name="stem_conv",
            )(x)
            x = norm(use_running_average=not train, dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        for stage, num_blocks in enumerate(stage_sizes):
            for block in range(num_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = block_cls(
                    filters=_STAGE_WIDTHS[stage],
                    strides=strides,
                    norm=norm,
                    dtype=self.dtype,
                )(x, train=train)

        x = jnp.mean(x, axis=(1, 2))  # global average pool -> (N, D)
        return x.astype(jnp.float32)


def feature_dim(base_cnn: str) -> int:
    """Encoder output dimensionality (512 for BasicBlock resnets, 2048 for
    the Bottleneck ones — models/arch.py FEATURE_DIMS)."""
    return FEATURE_DIMS[base_cnn]
