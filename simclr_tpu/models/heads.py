"""Projection head and downstream classifier heads (Flax linen).

Capability parity with ``/root/reference/model.py``:
  * :class:`ProjectionHead`  — Linear -> BN -> ReLU -> Linear(no bias)
    (``model.py:65-70``), hidden width = encoder feature dim.
  * :class:`LinearClassifier` — single affine probe (``model.py:7-21``).
  * :class:`NonLinearClassifier` — MLP probe. The reference *imports* this
    class but never ships it (latent defect, ``/root/reference/eval.py:16``;
    SURVEY.md §2.5.1); reconstructed here with the ProjectionHead shape
    (Linear -> BN -> ReLU -> Linear), the natural reading of the README's
    nonlinear-eval rows.
  * centroid probe — :func:`centroid_weights` builds per-class feature means
    and :func:`centroid_logits` scores ``x @ W`` (the reference's
    ``CentroidClassifier``, ``model.py:24-53``, as pure functions — it holds
    no learnable state, so a Module wrapper would be ceremony).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

Dtype = Any


def _tp_boundary_in(axis_name: str):
    """Megatron's f operator: identity forward, all-reduce backward.

    Applied where the replicated activation enters the tensor-parallel
    region: each shard's backward produces only its hidden-slice's partial
    ``dL/dh``; the psum on the cotangent completes the sum. A plain forward
    ``psum`` cannot be used for this because its transpose under shard_map
    is ``psum`` again, which would scale replicated cotangents by the axis
    size (pinned by tests/test_tp.py)."""

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, ct):
        return (jax.lax.psum(ct, axis_name),)

    f.defvjp(fwd, bwd)
    return f


def _tp_boundary_out(axis_name: str):
    """Megatron's g operator: all-reduce forward, identity backward.

    Applied where the partial row-parallel results leave the
    tensor-parallel region: the forward psum completes the contraction; the
    backward must hand each shard the plain replicated cotangent (psum's
    own transpose would multiply it by the axis size)."""

    @jax.custom_vjp
    def g(x):
        return jax.lax.psum(x, axis_name)

    def fwd(x):
        return jax.lax.psum(x, axis_name), None

    def bwd(_, ct):
        return (ct,)

    g.defvjp(fwd, bwd)
    return g


class ProjectionHead(nn.Module):
    """SimCLR non-linear projection g: h -> z.

    Tensor parallelism (Megatron MLP pattern, the ``model`` mesh axis):
    ``linear1`` is column-parallel (output channels sharded), ``bn1``/relu
    act on local channels, ``linear2`` is row-parallel (input channels
    sharded) with the f/g boundary operators handling the collectives in
    forward AND backward. Used from inside ``shard_map`` with the LOCAL
    view: set ``hidden`` to the per-shard width (global // tp) and
    ``tp_axis`` to the mesh axis. Init/checkpointing always use the GLOBAL
    view (defaults); the global (512, 512) kernel sharded
    ``P(None, 'model')`` presents each shard the (512, 512//tp) local
    kernel this module then expects (``parallel/tp.py``).
    """

    d: int = 128
    axis_name: str | None = None
    dtype: Dtype = jnp.bfloat16
    hidden: int | None = None  # per-shard hidden width; None = input width
    tp_axis: str | None = None

    @nn.compact
    def __call__(self, h, train: bool = True):
        hidden = self.hidden or h.shape[-1]
        if self.tp_axis is not None:
            h = _tp_boundary_in(self.tp_axis)(h)
        y = nn.Dense(hidden, dtype=self.dtype, param_dtype=jnp.float32, name="linear1")(h)
        y = nn.BatchNorm(
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.axis_name,
            name="bn1",
        )(y)
        y = nn.relu(y)
        y = nn.Dense(
            self.d, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32,
            name="linear2",
        )(y)
        if self.tp_axis is not None:
            # row-parallel contraction: each shard holds a partial sum over
            # its slice of the hidden dim; g operator completes it. Cast up
            # first: the unsharded head accumulates the full contraction
            # inside the matmul, so summing shard partials in bf16 would be
            # a TP-only numerical deviation (cheap — y is (B, d)).
            y = _tp_boundary_out(self.tp_axis)(y.astype(jnp.float32))
        return y.astype(jnp.float32)


class LinearClassifier(nn.Module):
    """Affine probe for the linear evaluation protocol."""

    num_classes: int = 10
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        return nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=jnp.float32,
            name="classifier",
        )(x)


class NonLinearClassifier(nn.Module):
    """MLP probe: Linear -> BN -> ReLU -> Linear (see module docstring)."""

    num_classes: int = 10
    hidden: int | None = None  # default: input width
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        hidden = self.hidden or x.shape[-1]
        y = nn.Dense(hidden, dtype=self.dtype, param_dtype=jnp.float32, name="linear1")(x)
        y = nn.BatchNorm(
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="bn1",
        )(y)
        y = nn.relu(y)
        return nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=jnp.float32, name="linear2"
        )(y)


def centroid_weights(features: jnp.ndarray, labels: jnp.ndarray, num_classes: int):
    """Per-class mean feature vectors, stacked as a (d, num_classes) matrix.

    Pure-JAX segment-mean version of the reference's
    ``CentroidClassifier.create_weights`` (``/root/reference/model.py:36-53``).
    """
    one_hot = jnp.eye(num_classes, dtype=features.dtype)[labels]  # (N, C)
    sums = features.T @ one_hot  # (d, C)
    counts = jnp.clip(one_hot.sum(axis=0), 1.0, None)  # (C,)
    return sums / counts


def centroid_logits(features: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
    """Scores = features @ weights, matching ``model.py:33-34``."""
    return features @ weights
