from simclr_tpu.models.contrastive import ContrastiveModel, SupervisedModel
from simclr_tpu.models.heads import (
    LinearClassifier,
    NonLinearClassifier,
    ProjectionHead,
    centroid_logits,
    centroid_weights,
)
from simclr_tpu.models.resnet import FEATURE_DIMS, ResNetEncoder, feature_dim

__all__ = [
    "ContrastiveModel",
    "SupervisedModel",
    "LinearClassifier",
    "NonLinearClassifier",
    "ProjectionHead",
    "centroid_logits",
    "centroid_weights",
    "ResNetEncoder",
    "FEATURE_DIMS",
    "feature_dim",
]
