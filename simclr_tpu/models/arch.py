"""ResNet zoo architecture tables — the single source of truth.

Consumed by the Flax encoder (``models/resnet.py``), the torch checkpoint
importer (``utils/torch_import.py``), and the reference-exact weight-decay
mask (``ops/lars.py``), so adding an architecture or changing a depth is a
one-file edit.

The reference zoo is {resnet18, resnet50} (``/root/reference/model.py:87``);
resnet34 (BasicBlock at resnet50's stage depths) and resnet101 (Bottleneck,
23-block stage 3) are additions.
"""

from __future__ import annotations

STAGE_SIZES: dict[str, tuple[int, int, int, int]] = {
    "resnet18": (2, 2, 2, 2),
    "resnet34": (3, 4, 6, 3),
    "resnet50": (3, 4, 6, 3),
    "resnet101": (3, 4, 23, 3),
}
STAGE_WIDTHS: tuple[int, int, int, int] = (64, 128, 256, 512)
BASIC_BLOCK_CNNS: tuple[str, ...] = ("resnet18", "resnet34")
# convs per residual block: 2 for BasicBlock, 3 for Bottleneck — also the
# Flax auto-index of the projection-shortcut BatchNorm (torch downsample.1)
CONVS_PER_BLOCK: dict[str, int] = {
    name: (2 if name in BASIC_BLOCK_CNNS else 3) for name in STAGE_SIZES
}
BLOCK_NAME: dict[str, str] = {
    name: ("BasicBlock" if name in BASIC_BLOCK_CNNS else "BottleneckBlock")
    for name in STAGE_SIZES
}
FEATURE_DIMS: dict[str, int] = {
    name: (STAGE_WIDTHS[-1] if name in BASIC_BLOCK_CNNS else STAGE_WIDTHS[-1] * 4)
    for name in STAGE_SIZES
}
# stages whose first block carries a projection shortcut (torch `downsample`):
# stages 2-4 always stride; stage 1 only widens channels for Bottleneck (the
# CIFAR stem outputs 64 = BasicBlock stage-1 width, but Bottleneck expands ×4)
DOWNSAMPLE_STAGES: dict[str, int] = {
    name: (3 if name in BASIC_BLOCK_CNNS else 4) for name in STAGE_SIZES
}
