"""Top-level model compositions: contrastive (encoder+head) and supervised.

Capability parity with ``/root/reference/model.py:76-168``:
  * :class:`ContrastiveModel` — encoder ``f`` + projection head ``g``;
    ``encode()`` returns pre-head features h (``model.py:116-123``),
    ``__call__`` returns projected z (``model.py:125-129``).
  * :class:`SupervisedModel` — encoder ``f`` + linear ``fc``
    (``model.py:132-168``).

Both expose ``train`` flags threading through BatchNorm; under a GSPMD ``jit``
with the batch sharded over the data mesh axis, BN statistics are global-batch
statistics (= reference SyncBN over the whole world).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from simclr_tpu.models.heads import ProjectionHead
from simclr_tpu.models.resnet import ResNetEncoder

Dtype = Any


class ContrastiveModel(nn.Module):
    """SimCLR model: z = g(f(x)). ``encode`` gives h = f(x)."""

    base_cnn: str = "resnet18"
    d: int = 128
    cifar_stem: bool = True
    dtype: Dtype = jnp.bfloat16
    bn_cross_replica_axis: str | None = None
    # tensor parallelism of the projection head (parallel/tp.py): the LOCAL
    # per-shard hidden width and the mesh axis the head is sharded over.
    # Defaults give the global (unsharded) view used for init/checkpoints.
    head_hidden: int | None = None
    head_tp_axis: str | None = None

    def setup(self):
        self.f = ResNetEncoder(
            base_cnn=self.base_cnn,
            cifar_stem=self.cifar_stem,
            dtype=self.dtype,
            bn_cross_replica_axis=self.bn_cross_replica_axis,
        )
        self.g = ProjectionHead(
            d=self.d,
            dtype=self.dtype,
            axis_name=self.bn_cross_replica_axis,
            hidden=self.head_hidden,
            tp_axis=self.head_tp_axis,
        )

    def encode(self, x, train: bool = True):
        return self.f(x, train=train)

    def project(self, h, train: bool = True):
        return self.g(h, train=train)

    def __call__(self, x, train: bool = True):
        h = self.encode(x, train=train)
        return self.g(h, train=train)


class SupervisedModel(nn.Module):
    """Encoder + linear classification layer (supervised baseline)."""

    base_cnn: str = "resnet18"
    num_classes: int = 10
    cifar_stem: bool = True
    dtype: Dtype = jnp.bfloat16
    bn_cross_replica_axis: str | None = None

    def setup(self):
        self.f = ResNetEncoder(
            base_cnn=self.base_cnn,
            cifar_stem=self.cifar_stem,
            dtype=self.dtype,
            bn_cross_replica_axis=self.bn_cross_replica_axis,
        )
        self.fc = nn.Dense(self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32)

    def encode(self, x, train: bool = True):
        return self.f(x, train=train)

    def __call__(self, x, train: bool = True):
        return self.fc(self.f(x, train=train))
