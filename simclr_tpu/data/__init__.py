from simclr_tpu.data.augment import (
    simclr_augment_single,
    simclr_two_views,
    to_float,
)
from simclr_tpu.data.cifar import (
    NUM_CLASSES,
    Dataset,
    load_dataset,
    synthetic_dataset,
)
from simclr_tpu.data.pipeline import EpochIterator, epoch_permutation
from simclr_tpu.data.prefetch import Prefetcher, prefetch

__all__ = [
    "simclr_augment_single",
    "simclr_two_views",
    "to_float",
    "NUM_CLASSES",
    "Dataset",
    "load_dataset",
    "synthetic_dataset",
    "EpochIterator",
    "epoch_permutation",
    "Prefetcher",
    "prefetch",
]
