"""Host-side input pipeline: shuffle, shard, batch, device feed.

Replaces the reference's ``DistributedSampler`` + ``DataLoader(num_workers=8,
pin_memory, drop_last)`` stack (``/root/reference/main.py:169-173``) with the
SPMD-native shape: ONE process per host iterates the epoch, draws globally
shuffled batches, keeps only its own host's rows, and ``device_put``s them
with a batch-sharded ``NamedSharding`` so every chip holds exactly its shard.
Augmentation happens on device inside the jitted step (see
``data/augment.py``), so the host only moves raw uint8.

Parity points (SURVEY §2.5.11):
  * per-epoch reshuffle seeded by ``seed + epoch`` — DistributedSampler's
    ``set_epoch`` determinism (``/root/reference/main.py:101-102``);
  * ``drop_last=True`` truncation: ``steps = N // global_batch``;
  * global batch = per-device batch x number of data shards, matching the
    reference's per-GPU ``experiment.batches`` semantics.
"""

from __future__ import annotations

from collections.abc import Iterator

import jax
import numpy as np

from simclr_tpu.data.cifar import Dataset
from simclr_tpu.native.lib import DEFAULT_THREADS, gather_rows2
from simclr_tpu.parallel.mesh import put_global_batch


def epoch_permutation(num_samples: int, seed: int, epoch: int) -> np.ndarray:
    """Deterministic per-epoch shuffle (DistributedSampler ``set_epoch``)."""
    return np.random.default_rng(np.uint64(seed) + np.uint64(epoch)).permutation(
        num_samples
    )


def epoch_index_matrix(
    num_samples: int, seed: int, epoch: int, steps: int, global_batch: int
) -> np.ndarray:
    """(steps, global_batch) int32 shuffled row indices for one epoch.

    The epoch-compiled training paths feed this to the on-device gather;
    truncation matches :class:`EpochIterator`'s ``drop_last`` semantics, so
    the data order is identical to the per-step pipeline (load-bearing for
    the epoch-compile equivalence guarantee, tests/test_epoch_compile.py).
    """
    order = epoch_permutation(num_samples, seed, epoch)
    return order[: steps * global_batch].reshape(steps, global_batch).astype(np.int32)


class EpochIterator:
    """Iterates one split in globally-shuffled, host-sharded batches.

    Yields dicts with uint8 ``image`` (host-local rows of the global batch)
    and int32 ``label``. With ``sharding`` set, arrays are ``device_put`` so
    downstream ``jit`` consumes already-sharded global arrays (single-host:
    the full global batch; multi-host: this host's rows assembled into a
    global array via ``make_array_from_process_local_data``).
    """

    def __init__(
        self,
        dataset: Dataset,
        global_batch: int,
        seed: int = 0,
        shuffle: bool = True,
        sharding: jax.sharding.NamedSharding | None = None,
        drop_last: bool = True,
        gather_threads: int | None = None,
    ):
        if global_batch <= 0:
            raise ValueError("global_batch must be positive")
        self.dataset = dataset
        self.global_batch = global_batch
        self.seed = seed
        self.shuffle = shuffle
        self.sharding = sharding
        self.drop_last = drop_last
        # native gather thread-pool width; the reference's parameter
        # 'num_workers' (DataLoader workers) maps here. 0 means
        # single-threaded (like num_workers=0), not "use the default".
        self.gather_threads = (
            gather_threads if gather_threads is not None else DEFAULT_THREADS
        )
        n = len(dataset)
        self.steps_per_epoch = n // global_batch if drop_last else -(-n // global_batch)
        if self.steps_per_epoch == 0:
            raise ValueError(
                f"dataset of {n} samples smaller than global batch {global_batch}"
            )
        if not drop_last and sharding is not None and n % global_batch:
            raise ValueError(
                f"drop_last=False with a device sharding requires the dataset "
                f"size ({n}) to divide the global batch ({global_batch}): a "
                f"partial final batch cannot be laid out over the mesh (pad "
                f"the tail on the host instead, as supervised.py does)"
            )
        n_proc = jax.process_count()
        if global_batch % n_proc:
            raise ValueError(
                f"global batch {global_batch} must be divisible by the "
                f"process count {n_proc}; otherwise hosts would silently "
                f"drop {global_batch % n_proc} rows per step"
            )

    def _order(self, epoch: int) -> np.ndarray:
        if self.shuffle:
            return epoch_permutation(len(self.dataset), self.seed, epoch)
        return np.arange(len(self.dataset))

    def batches(self, epoch: int) -> Iterator[dict[str, np.ndarray | jax.Array]]:
        order = self._order(epoch)
        n_proc = jax.process_count()
        proc = jax.process_index()
        for step in range(self.steps_per_epoch):
            idx = order[step * self.global_batch : (step + 1) * self.global_batch]
            # each host materializes only its contiguous row block
            per_host = len(idx) // n_proc if n_proc > 1 else len(idx)
            local_idx = idx[proc * per_host : (proc + 1) * per_host]
            # native multithreaded row gather (numpy-take fallback inside)
            images, labels = gather_rows2(
                self.dataset.images, self.dataset.labels, local_idx,
                n_threads=self.gather_threads,
            )
            batch = {"image": images, "label": labels}
            if self.sharding is not None:
                batch = {
                    k: self._to_device(v, k) for k, v in batch.items()
                }
            yield batch

    def _to_device(self, array: np.ndarray, name: str) -> jax.Array:
        return put_global_batch(array, self.sharding)
