"""On-device SimCLR augmentations (pure JAX, jit/vmap-friendly).

The reference runs torchvision CPU transforms in DataLoader worker processes
(``/root/reference/dataset.py:19-38``): RandomResizedCrop(32) -> HFlip(0.5)
-> RandomApply(ColorJitter(0.8s, 0.8s, 0.8s, 0.2s), p=0.8) ->
RandomGrayscale(0.2) -> ToTensor. No Gaussian blur, no mean/std normalize
(correct for CIFAR per the SimCLR paper — SURVEY §2.5.9-10).

TPU-first redesign: augmentation is a jitted, vmapped, per-example-keyed
function that runs ON DEVICE as part of the train step. The host feeds raw
uint8 batches; the two stochastic views are produced by the same XLA program
that consumes them, so there is no per-worker CPU bottleneck and no H2D
traffic beyond the raw images. All shapes are static: the data-dependent
crop/resize is expressed as two (out, in) bilinear sampling matrices applied
as matmuls, with traced crop-box coordinates and coordinates clamped inside
the box (see :func:`random_resized_crop`), and the random-order color jitter
uses ``lax.switch`` over op indices.

Distribution parity with torchvision (the likeliest silent-accuracy-gap
source, SURVEY §7 hard part c):
  * RandomResizedCrop: 10 vectorized attempts of (area scale U(0.08,1),
    log-aspect U(log 3/4, log 4/3)), first in-bounds attempt wins, center-crop
    fallback — same rejection-sampling distribution as torchvision's loop.
  * ColorJitter: brightness/contrast/saturation factors U(max(0,1-0.8s),
    1+0.8s), hue shift U(-0.2s, 0.2s), applied in a uniformly random order of
    the four ops; the whole jitter applied with probability 0.8.
  * Grayscale: ITU-R 601 luma (0.299, 0.587, 0.114), p=0.2.

Images are float32 in [0,1], NHWC.
"""

from __future__ import annotations

import itertools
import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# torchvision RandomResizedCrop defaults (scale, ratio) and attempt count.
# Host-side constants (numpy/math, not jnp) so importing this module never
# initializes a JAX backend.
_CROP_SCALE = (0.08, 1.0)
_CROP_LOG_RATIO = (math.log(3.0 / 4.0), math.log(4.0 / 3.0))
_CROP_ATTEMPTS = 10

_GRAY_WEIGHTS = np.array([0.299, 0.587, 0.114], dtype=np.float32)


def to_float(image: jnp.ndarray) -> jnp.ndarray:
    """uint8 [0,255] -> float32 [0,1] (torchvision ToTensor semantics)."""
    if image.dtype == jnp.uint8:
        return image.astype(jnp.float32) / 255.0
    return image.astype(jnp.float32)


# ---------------------------------------------------------------------------
# RandomResizedCrop
# ---------------------------------------------------------------------------

def _sample_crop_box(key: jax.Array, height: int, width: int):
    """Sample (top, left, h, w) floats per torchvision RandomResizedCrop.

    Vectorized form of the reference transform's 10-attempt rejection loop:
    all attempts are sampled at once, the first in-bounds one is selected,
    and the torchvision center-crop fallback (aspect clamped to the ratio
    range) is used when every attempt misses.
    """
    k_area, k_ratio, k_top, k_left = jax.random.split(key, 4)
    area = float(height * width)

    target_area = area * jax.random.uniform(
        k_area, (_CROP_ATTEMPTS,), minval=_CROP_SCALE[0], maxval=_CROP_SCALE[1]
    )
    aspect = jnp.exp(
        jax.random.uniform(
            k_ratio,
            (_CROP_ATTEMPTS,),
            minval=_CROP_LOG_RATIO[0],
            maxval=_CROP_LOG_RATIO[1],
        )
    )
    # torchvision rounds w/h to ints before the bounds check
    w = jnp.round(jnp.sqrt(target_area * aspect))
    h = jnp.round(jnp.sqrt(target_area / aspect))
    valid = (w > 0) & (w <= width) & (h > 0) & (h <= height)
    # first valid attempt (argmax returns the first True)
    pick = jnp.argmax(valid)
    any_valid = jnp.any(valid)

    w_pick = w[pick]
    h_pick = h[pick]
    # uniform placement: torchvision samples integer top/left in
    # [0, H-h] x [0, W-w] inclusive
    u_top = jax.random.uniform(k_top)
    u_left = jax.random.uniform(k_left)
    top = jnp.floor(u_top * (height - h_pick + 1.0))
    left = jnp.floor(u_left * (width - w_pick + 1.0))

    # fallback: central crop with aspect clamped into the ratio range
    in_ratio = width / height
    fb_w = jnp.where(
        in_ratio < jnp.exp(_CROP_LOG_RATIO[0]),
        float(width),
        jnp.where(
            in_ratio > jnp.exp(_CROP_LOG_RATIO[1]),
            jnp.round(height * jnp.exp(_CROP_LOG_RATIO[1])),
            float(width),
        ),
    )
    fb_h = jnp.where(
        in_ratio < jnp.exp(_CROP_LOG_RATIO[0]),
        jnp.round(width / jnp.exp(_CROP_LOG_RATIO[0])),
        jnp.where(in_ratio > jnp.exp(_CROP_LOG_RATIO[1]), float(height), float(height)),
    )
    fb_top = jnp.round((height - fb_h) / 2.0)
    fb_left = jnp.round((width - fb_w) / 2.0)

    top = jnp.where(any_valid, top, fb_top)
    left = jnp.where(any_valid, left, fb_left)
    h_out = jnp.where(any_valid, h_pick, fb_h)
    w_out = jnp.where(any_valid, w_pick, fb_w)
    return top, left, h_out, w_out


def _axis_resize_weights(
    origin: jnp.ndarray, size: jnp.ndarray, out_size: int, in_size: int
) -> jnp.ndarray:
    """(out_size, in_size) bilinear sampling matrix for one axis.

    Sample centers follow the half-pixel convention torch/PIL use
    (``src = origin + (dst + 0.5) * size/out - 0.5``) and are CLAMPED to the
    crop box, so border pixels replicate the box edge exactly as a
    crop-then-resize does — never bleeding into source pixels outside the
    sampled box.
    """
    centers = origin + (jnp.arange(out_size, dtype=jnp.float32) + 0.5) * (
        size / out_size
    ) - 0.5
    centers = jnp.clip(centers, origin, origin + size - 1.0)
    i0 = jnp.floor(centers)
    frac = centers - i0
    i0 = jnp.clip(i0.astype(jnp.int32), 0, in_size - 1)
    i1 = jnp.clip(i0 + 1, 0, in_size - 1)
    rows = jnp.arange(out_size)
    weights = jnp.zeros((out_size, in_size), jnp.float32)
    weights = weights.at[rows, i0].add(1.0 - frac)
    weights = weights.at[rows, i1].add(frac)
    return weights


def random_resized_crop(
    key: jax.Array, image: jnp.ndarray, out_size: int = 32
) -> jnp.ndarray:
    """Crop a random box and resize to (out_size, out_size) bilinearly.

    The dynamic-size crop + static-size resize is expressed as two static
    (out, H)/(out, W) sampling matrices applied as matmuls (MXU-friendly, no
    dynamic shapes), with sample coordinates clamped inside the crop box —
    matching crop-then-resize edge behavior. Remaining documented deviation
    from torchvision: PIL antialiases when downscaling; this is plain
    bilinear.
    """
    height, width = image.shape[0], image.shape[1]
    top, left, crop_h, crop_w = _sample_crop_box(key, height, width)

    w_rows = _axis_resize_weights(top, crop_h, out_size, height)      # (out, H)
    w_cols = _axis_resize_weights(left, crop_w, out_size, width)      # (out, W)
    img = image.astype(jnp.float32)
    return jnp.einsum("oh,hwc,pw->opc", w_rows, img, w_cols)


# ---------------------------------------------------------------------------
# Color ops (torchvision functional semantics on [0,1] floats)
# ---------------------------------------------------------------------------

def _grayscale(image: jnp.ndarray) -> jnp.ndarray:
    luma = jnp.tensordot(image, _GRAY_WEIGHTS, axes=[[-1], [0]])
    return luma[..., None] * jnp.ones((1, 1, image.shape[-1]), image.dtype)


def adjust_brightness(image: jnp.ndarray, factor: jnp.ndarray) -> jnp.ndarray:
    return jnp.clip(image * factor, 0.0, 1.0)


def adjust_contrast(image: jnp.ndarray, factor: jnp.ndarray) -> jnp.ndarray:
    # torchvision blends with the MEAN OF THE GRAYSCALE image
    mean = _grayscale(image).mean()
    return jnp.clip(mean + factor * (image - mean), 0.0, 1.0)


def adjust_saturation(image: jnp.ndarray, factor: jnp.ndarray) -> jnp.ndarray:
    gray = _grayscale(image)
    return jnp.clip(gray + factor * (image - gray), 0.0, 1.0)


def adjust_hue(image: jnp.ndarray, delta: jnp.ndarray) -> jnp.ndarray:
    """Shift hue by ``delta`` (in turns, torchvision range [-0.5, 0.5])."""
    r, g, b = image[..., 0], image[..., 1], image[..., 2]
    maxc = jnp.maximum(jnp.maximum(r, g), b)
    minc = jnp.minimum(jnp.minimum(r, g), b)
    value = maxc
    chroma = maxc - minc
    safe_chroma = jnp.where(chroma > 0, chroma, 1.0)
    sat = jnp.where(maxc > 0, chroma / jnp.where(maxc > 0, maxc, 1.0), 0.0)

    hue = jnp.where(
        maxc == r,
        ((g - b) / safe_chroma) % 6.0,
        jnp.where(maxc == g, (b - r) / safe_chroma + 2.0, (r - g) / safe_chroma + 4.0),
    )
    hue = jnp.where(chroma > 0, hue / 6.0, 0.0)
    hue = (hue + delta) % 1.0

    # HSV -> RGB
    h6 = hue * 6.0
    i = jnp.floor(h6)
    f = h6 - i
    p = value * (1.0 - sat)
    q = value * (1.0 - sat * f)
    t = value * (1.0 - sat * (1.0 - f))
    i = i.astype(jnp.int32) % 6

    r_out = jnp.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5], [value, q, p, p, t, value]
    )
    g_out = jnp.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5], [t, value, value, q, p, p]
    )
    b_out = jnp.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5], [p, p, t, value, value, q]
    )
    return jnp.clip(jnp.stack([r_out, g_out, b_out], axis=-1), 0.0, 1.0)


_JITTER_PERMS = np.array(list(itertools.permutations(range(4))), dtype=np.int32)


def jitter_params(key: jax.Array, strength: float = 0.5):
    """Sample ColorJitter(0.8s, 0.8s, 0.8s, 0.2s) parameters: the three
    blend factors U(max(0,1-r), 1+r), the hue shift U(-h, h), and the op
    permutation index (uniform over all 24 orders). Factored out of
    :func:`color_jitter` so distribution tests measure the SAME sampler the
    pipeline runs (tests/test_augment_distribution.py)."""
    b, c, s, h = 0.8 * strength, 0.8 * strength, 0.8 * strength, 0.2 * strength
    k_b, k_c, k_s, k_h, k_perm = jax.random.split(key, 5)
    f_b = jax.random.uniform(k_b, minval=max(0.0, 1.0 - b), maxval=1.0 + b)
    f_c = jax.random.uniform(k_c, minval=max(0.0, 1.0 - c), maxval=1.0 + c)
    f_s = jax.random.uniform(k_s, minval=max(0.0, 1.0 - s), maxval=1.0 + s)
    f_h = jax.random.uniform(k_h, minval=-h, maxval=h)
    perm_idx = jax.random.randint(k_perm, (), 0, _JITTER_PERMS.shape[0])
    return f_b, f_c, f_s, f_h, perm_idx


def color_jitter(
    key: jax.Array, image: jnp.ndarray, strength: float = 0.5
) -> jnp.ndarray:
    """torchvision ColorJitter(0.8s, 0.8s, 0.8s, 0.2s) with random op order."""
    f_b, f_c, f_s, f_h, perm_idx = jitter_params(key, strength)

    ops = [
        lambda img: adjust_brightness(img, f_b),
        lambda img: adjust_contrast(img, f_c),
        lambda img: adjust_saturation(img, f_s),
        lambda img: adjust_hue(img, f_h),
    ]
    perm = jnp.asarray(_JITTER_PERMS)[perm_idx]
    for slot in range(4):
        image = lax.switch(perm[slot], ops, image)
    return image


def random_grayscale(key: jax.Array, image: jnp.ndarray, p: float = 0.2) -> jnp.ndarray:
    apply = jax.random.uniform(key) < p
    return jnp.where(apply, _grayscale(image), image)


def random_hflip(key: jax.Array, image: jnp.ndarray, p: float = 0.5) -> jnp.ndarray:
    apply = jax.random.uniform(key) < p
    return jnp.where(apply, image[:, ::-1, :], image)


# ---------------------------------------------------------------------------
# Full pipelines
# ---------------------------------------------------------------------------

# reference pipeline probabilities (dataset.py:27-35): RandomApply(jitter)
# p=0.8, RandomGrayscale 0.2, RandomHorizontalFlip 0.5
_JITTER_APPLY_P = 0.8
_GRAYSCALE_P = 0.2
_HFLIP_P = 0.5


def _view_keys(key: jax.Array):
    """The one per-view key split (crop, flip, jitter-gate, jitter, gray) —
    shared with tests that reconstruct individual pipeline branches."""
    return jax.random.split(key, 5)


def simclr_augment_single(
    key: jax.Array,
    image: jnp.ndarray,
    strength: float = 0.5,
    out_size: int = 32,
) -> jnp.ndarray:
    """One stochastic SimCLR view of one image (HWC float32 in [0, 1]).

    Callers convert uint8 once per IMAGE via :func:`to_float` before
    vmapping this over views (it used to live here, paying the dequant once
    per view); the fused Pallas kernel (``ops/augment_pallas.py``)
    dequantizes in-VMEM instead and never materializes the float image in
    HBM at all.
    """
    image = image.astype(jnp.float32)
    k_crop, k_flip, k_apply, k_jitter, k_gray = _view_keys(key)
    image = random_resized_crop(k_crop, image, out_size=out_size)
    image = random_hflip(k_flip, image, p=_HFLIP_P)
    jittered = color_jitter(k_jitter, image, strength=strength)
    image = jnp.where(jax.random.uniform(k_apply) < _JITTER_APPLY_P, jittered, image)
    image = random_grayscale(k_gray, image, p=_GRAYSCALE_P)
    return image


@partial(jax.jit, static_argnames=("strength", "out_size"))
def simclr_two_views(
    key: jax.Array,
    images: jnp.ndarray,
    strength: float = 0.5,
    out_size: int = 32,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Two independent augmented views of a batch (N,H,W,C).

    Mirrors ``SimCLRTransforms.__call__`` returning two independent draws
    (``/root/reference/dataset.py:49-50``), vectorized over the batch with
    per-example PRNG keys. uint8 input converts to float ONCE here (not
    once per view — :func:`simclr_augment_single` takes floats).
    """
    images = to_float(images)
    n = images.shape[0]
    keys = jax.random.split(key, 2 * n)
    aug = jax.vmap(simclr_augment_single, in_axes=(0, 0, None, None))
    view0 = aug(keys[:n], images, strength, out_size)
    view1 = aug(keys[n:], images, strength, out_size)
    return view0, view1
