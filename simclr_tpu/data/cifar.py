"""CIFAR-10/100 ingestion without torchvision.

The reference leans on ``torchvision.datasets.CIFAR10/100(download=True)``
(``/root/reference/main.py:158-165``). This module is a first-party reader for
the standard "python version" pickle archives:

  * CIFAR-10:  ``<data_dir>/cifar-10-batches-py/{data_batch_1..5, test_batch}``
  * CIFAR-100: ``<data_dir>/cifar-100-python/{train, test}``

Images are returned as one contiguous uint8 array in NHWC layout (TPU-native;
the archives store CHW-flattened rows) plus an int32 label vector — the whole
of CIFAR fits in host RAM (~180 MB), so there is no per-item lazy loading and
the device feed is a simple sliced `device_put` per step.

When the archives are absent (this build environment has no network egress),
``load_dataset(..., synthetic_ok=True)`` produces a deterministic synthetic
dataset with the same shapes/dtypes and class-conditional structure, so every
entry point, test, and benchmark runs end-to-end; quality numbers obviously
require the real archives.
"""

from __future__ import annotations

import os
import pickle
import tarfile
from dataclasses import dataclass

import numpy as np

NUM_CLASSES = {"cifar10": 10, "cifar100": 100}
_TRAIN_SIZES = {"cifar10": 50000, "cifar100": 50000}
_TEST_SIZES = {"cifar10": 10000, "cifar100": 10000}

DEFAULT_DATA_DIR = os.environ.get("SIMCLR_DATA_DIR", os.path.expanduser("~/data"))

# sigma of the synthetic fallback's per-instance low-frequency field (the
# iid texture rides at a quarter of it) when unspecified — the single
# source the yaml comments ("null -> 24") refer to
DEFAULT_SYNTHETIC_NOISE = 24.0


@dataclass(frozen=True)
class Dataset:
    """An in-memory split: images uint8 (N,32,32,3) NHWC, labels int32 (N,)."""

    images: np.ndarray
    labels: np.ndarray
    name: str
    split: str
    synthetic: bool = False

    def __len__(self) -> int:
        return len(self.images)

    @property
    def num_classes(self) -> int:
        return NUM_CLASSES[self.name]


def _rows_to_nhwc(rows: np.ndarray) -> np.ndarray:
    """(N, 3072) CHW-flat rows -> (N, 32, 32, 3) uint8 NHWC."""
    return rows.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).copy()


def _unpickle(path: str) -> dict:
    with open(path, "rb") as f:
        return pickle.load(f, encoding="bytes")


def _maybe_extract(archive: str, data_dir: str) -> None:
    if os.path.exists(archive):
        with tarfile.open(archive, "r:gz") as tar:
            tar.extractall(data_dir)  # noqa: S202 - local trusted archive


def _load_cifar10(data_dir: str, split: str) -> tuple[np.ndarray, np.ndarray]:
    base = os.path.join(data_dir, "cifar-10-batches-py")
    if not os.path.isdir(base):
        _maybe_extract(os.path.join(data_dir, "cifar-10-python.tar.gz"), data_dir)
    files = (
        [f"data_batch_{i}" for i in range(1, 6)] if split == "train" else ["test_batch"]
    )
    rows, labels = [], []
    for fname in files:
        batch = _unpickle(os.path.join(base, fname))
        rows.append(np.asarray(batch[b"data"], dtype=np.uint8))
        labels.extend(batch[b"labels"])
    return _rows_to_nhwc(np.concatenate(rows)), np.asarray(labels, dtype=np.int32)


def _load_cifar100(data_dir: str, split: str) -> tuple[np.ndarray, np.ndarray]:
    base = os.path.join(data_dir, "cifar-100-python")
    if not os.path.isdir(base):
        _maybe_extract(os.path.join(data_dir, "cifar-100-python.tar.gz"), data_dir)
    batch = _unpickle(os.path.join(base, "train" if split == "train" else "test"))
    rows = np.asarray(batch[b"data"], dtype=np.uint8)
    labels = np.asarray(batch[b"fine_labels"], dtype=np.int32)
    return _rows_to_nhwc(rows), labels


def synthetic_dataset(
    name: str, split: str, size: int | None = None, seed: int = 0,
    noise: float | None = None,
) -> Dataset:
    """Deterministic class-conditional fake CIFAR (same shapes/dtypes).

    Each class gets a fixed random 32x32x3 prototype; each sample adds a
    per-instance LOW-FREQUENCY field (an 8x8 Gaussian field, sigma
    ``noise``, upsampled 4x) plus mild iid texture (sigma/4). The
    low-frequency component matters: like real image content — and unlike
    iid pixel noise — it partially survives RandomResizedCrop + resize, so
    views of one instance carry a view-stable instance signal and NT-Xent
    has a learnable objective.

    Generator designs that measurably FAIL under the real recipe are kept
    on record (docs/convergence_r5*.log, round 5): iid-only noise around
    prototypes has no view-stable instance signal, and training falls into
    the uniform collapse (loss pinned at ln(2N-1), constant predictions
    from ~step 25); a pasted class-object on an instance background (a
    14x14 or even 22x22 patch) lets the encoder solve instance
    discrimination from backgrounds alone and class structure never
    emerges within a CPU-scale step budget. The prototype+smooth-field
    form here is the one whose class structure demonstrably RISES from
    the chance-level random-init anchor within tens of steps; over longer
    horizons instance discrimination competes with centroid-readable
    class structure (instances of a class ARE deviations from its
    prototype), which is exactly the synthetic-vs-natural-data gap the
    learning tests account for (tests/test_convergence.py).

    A RANDOM-init encoder's centroid probe reads ~chance on this data
    (measured; the probes' numbers prove learned features, not pixel
    separability).
    """
    num_classes = NUM_CLASSES[name]
    if size is None:
        size = _TRAIN_SIZES[name] if split == "train" else _TEST_SIZES[name]
    # class objects are SHARED across splits (train and test must mean
    # the same thing by "class k"); only the per-sample content differs
    proto_rng = np.random.default_rng(seed)
    noise_rng = np.random.default_rng(seed + (1000 if split == "train" else 2000))
    # float32/uint8 throughout: the default 50k split would otherwise build
    # multi-GB int64/float64 temporaries on the small smoke-test hosts this
    # fallback exists for
    prototypes = proto_rng.integers(0, 256, size=(num_classes, 32, 32, 3)).astype(
        np.float32
    )
    labels = np.arange(size, dtype=np.int32) % num_classes
    sigma = DEFAULT_SYNTHETIC_NOISE if noise is None else float(noise)
    # per-instance low-frequency field (8x8, scaled small) + iid texture,
    # combined IN PLACE in one full-size buffer: a second (size,32,32,3)
    # f32 array or a kron temp would double peak memory at the 50k default
    # split (the hazard the dtype comment above exists for). The broadcast
    # view add is the 4x nearest-upsample.
    field = noise_rng.standard_normal(size=(size, 8, 8, 3), dtype=np.float32)
    field *= sigma
    pixels = noise_rng.standard_normal(size=(size, 32, 32, 3), dtype=np.float32)
    pixels *= sigma / 4.0  # iid texture
    pixels.reshape(size, 8, 4, 8, 4, 3)[...] += field[:, :, None, :, None, :]
    # per-class in-place add: prototypes[labels] would materialize a second
    # full-size (size,32,32,3) f32 temporary, doubling peak memory; per-class
    # fancy-index adds peak at ~size/num_classes rows instead
    for c in range(num_classes):
        pixels[labels == c] += prototypes[c]
    images = np.clip(pixels, 0, 255, out=pixels).astype(np.uint8)
    return Dataset(images=images, labels=labels, name=name, split=split, synthetic=True)


def load_dataset(
    name: str,
    split: str = "train",
    data_dir: str | None = None,
    synthetic_ok: bool = False,
    synthetic_size: int | None = None,
    synthetic_noise: float | None = None,
) -> Dataset:
    """Load a CIFAR split from disk, optionally falling back to synthetic.

    ``name`` in {cifar10, cifar100}; ``split`` in {train, test}. The reference
    branches identically on ``experiment.name`` (``/root/reference/main.py:158-165``).
    """
    if name not in NUM_CLASSES:
        raise ValueError(f"dataset must be cifar10|cifar100, got {name!r}")
    if split not in ("train", "test"):
        raise ValueError(f"split must be train|test, got {split!r}")
    data_dir = data_dir or DEFAULT_DATA_DIR
    loader = _load_cifar10 if name == "cifar10" else _load_cifar100
    try:
        images, labels = loader(data_dir, split)
        return Dataset(images=images, labels=labels, name=name, split=split)
    except (FileNotFoundError, NotADirectoryError):
        if not synthetic_ok:
            raise FileNotFoundError(
                f"{name} archives not found under {data_dir!r}; place the "
                f"standard python-version archives there, or pass "
                f"synthetic_ok=True (experiment.synthetic_data=true) for a "
                f"deterministic synthetic stand-in"
            ) from None
        return synthetic_dataset(
            name, split, size=synthetic_size, noise=synthetic_noise,
        )
