"""Background prefetcher: overlap batch assembly + H2D with the device step.

The capability the reference buys with ``DataLoader(num_workers=8,
pin_memory=True)`` (``/root/reference/main.py:170-173``) — keeping the
accelerator fed while the CPU prepares the next batch — reshaped for SPMD:
one daemon thread per process assembles upcoming batches (native C++ row
gather, ``simclr_tpu/native``) and ``device_put``s them so the transfer
overlaps the in-flight XLA step. Queue depth 2 is enough: JAX dispatch is
async, so the host loop runs ahead of the device by design; the prefetcher
just keeps gather+transfer off the critical path.

The queue-and-drain discipline here is the template the serving batcher
(``simclr_tpu/serve/batcher.py``) reuses: every blocking queue operation is
bounded by a timeout against a liveness flag, so a wedged producer can
neither deadlock the consumer nor hang interpreter shutdown.
"""

from __future__ import annotations

import queue
import threading
import time
from collections.abc import Iterator
from typing import Any

_SENTINEL = object()

# bound on every internal blocking queue op: long enough to stay off the hot
# path, short enough that stop/done flags are observed promptly
_POLL_S = 0.1


class Prefetcher:
    """Wraps any batch iterator; yields the same batches, prefetched.

    Exceptions in the worker are re-raised in the consumer's ``__next__``
    (after any batches produced before the failure — they are valid work).
    Always used as a context manager or fully drained; ``close()`` stops
    early and returns within its join timeout even if the producer is
    wedged inside the wrapped iterator.
    """

    def __init__(self, iterator: Iterator[Any], depth: int = 2):
        self._q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._error: BaseException | None = None
        self._stop = threading.Event()
        self._done = threading.Event()

        def worker():
            try:
                for item in iterator:
                    if self._stop.is_set():
                        return
                    # bounded put: a consumer that stopped reading (close(),
                    # crash) must not leave this thread blocked forever on a
                    # full queue
                    while not self._stop.is_set():
                        try:
                            self._q.put(item, timeout=_POLL_S)
                            break
                        except queue.Full:
                            continue
            except BaseException as e:  # noqa: BLE001 - relayed to consumer
                self._error = e
            finally:
                # publish completion BEFORE the sentinel: if the queue is
                # full the sentinel is dropped and __next__ falls back to
                # the done flag, so termination (and the error) still
                # reaches the consumer
                self._done.set()
                try:
                    self._q.put_nowait(_SENTINEL)
                except queue.Full:
                    pass

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            try:
                item = self._q.get(timeout=_POLL_S)
            except queue.Empty:
                if self._done.is_set():
                    item = _SENTINEL  # sentinel was dropped on a full queue
                else:
                    continue
            if item is _SENTINEL:
                self._thread.join(timeout=5)
                if self._error is not None:
                    raise self._error
                raise StopIteration
            return item

    def close(self, timeout: float = 5.0) -> None:
        """Stop the worker and join it, draining the queue so a producer
        blocked on a full queue can exit. Returns after at most ``timeout``
        seconds: the worker is a daemon thread, so a producer wedged inside
        the wrapped iterator (e.g. a hung device transfer) is abandoned
        rather than allowed to hang interpreter shutdown."""
        self._stop.set()
        deadline = time.monotonic() + timeout
        while self._thread.is_alive():
            try:
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            self._thread.join(timeout=min(_POLL_S, remaining))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def prefetch(iterator: Iterator[Any], depth: int = 2) -> Prefetcher:
    return Prefetcher(iterator, depth=depth)
