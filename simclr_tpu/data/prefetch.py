"""Background prefetcher: overlap batch assembly + H2D with the device step.

The capability the reference buys with ``DataLoader(num_workers=8,
pin_memory=True)`` (``/root/reference/main.py:170-173``) — keeping the
accelerator fed while the CPU prepares the next batch — reshaped for SPMD:
one daemon thread per process assembles upcoming batches (native C++ row
gather, ``simclr_tpu/native``) and ``device_put``s them so the transfer
overlaps the in-flight XLA step. Queue depth 2 is enough: JAX dispatch is
async, so the host loop runs ahead of the device by design; the prefetcher
just keeps gather+transfer off the critical path.
"""

from __future__ import annotations

import queue
import threading
from collections.abc import Iterator
from typing import Any

_SENTINEL = object()


class Prefetcher:
    """Wraps any batch iterator; yields the same batches, prefetched.

    Exceptions in the worker are re-raised in the consumer. Always used as a
    context manager or fully drained; ``close()`` stops early.
    """

    def __init__(self, iterator: Iterator[Any], depth: int = 2):
        self._q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._error: BaseException | None = None
        self._stop = threading.Event()

        def worker():
            try:
                for item in iterator:
                    if self._stop.is_set():
                        return
                    self._q.put(item)
            except BaseException as e:  # noqa: BLE001 - relayed to consumer
                self._error = e
            finally:
                self._q.put(_SENTINEL)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is _SENTINEL:
            self._thread.join()
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item

    def close(self):
        self._stop.set()
        # drain so the worker unblocks from a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def prefetch(iterator: Iterator[Any], depth: int = 2) -> Prefetcher:
    return Prefetcher(iterator, depth=depth)
