"""Training-side telemetry registry (the ``simclr_train_*`` metric set).

One :class:`Telemetry` per run, wired into ``main.py``/``supervised.py`` and
scraped by ``obs/exporter.py``. The cardinal design rule (Podracer,
PAPERS.md: monitoring must cost zero host syncs): every update takes only
host-side floats the training loop ALREADY fetched through its
``utils/profiling.synchronize`` value fences — the epoch loss, the schedule
lr, wall-clock epoch durations. Rendering ``/metrics`` reads those floats
back; no method here ever touches a ``jax.Array``, so a scrape can never
add a device round-trip to the hot loop.

MFU reuses the analytic FLOP model from ``scripts/roofline_model.py`` (the
same math that defended the measured 49% MFU as a ceiling fraction): FLOPs
per device-step divided by measured step time over the v5e bf16 peak.
Grad-allreduce wire bytes come from
:func:`simclr_tpu.parallel.compress.allreduce_wire_bytes` — analytic, per
device, per step.
"""

from __future__ import annotations

import os
import threading
import time

from simclr_tpu.obs.metrics import Counter, Gauge, Histogram, Summary

# v5e bf16 peak, mirrored from scripts/roofline_model.py (scripts/ is not a
# package; the FLOP model itself is file-loaded below so the math has one
# home, but the peak constant is needed even when scripts/ is absent)
PEAK_FLOPS = 197e12

# step-time bucket bounds (seconds): 1 ms (CIFAR-small steps on chip) up
# through minutes (epoch_compile ticks once per epoch)
STEP_TIME_BUCKETS = (
    0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


def _roofline_flops_per_step(
    arch: str, per_device_batch: int, d: int, augment_impl: str = "xla"
) -> float | None:
    """Total FLOPs of one per-device train step from the roofline model.

    ``scripts/`` is not a package, so the model is loaded by file path
    relative to the repo root; an installed-without-scripts tree degrades to
    ``None`` (MFU gauge stays 0) rather than failing the run.

    ``augment_impl`` selects the augmentation row's byte accounting (the
    fused Pallas kernel reclaims HBM bandwidth); the step's FLOPs are
    impl-invariant today, but threading the knob keeps the live MFU and
    drift gauges attributed to the program actually running.
    """
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "scripts",
        "roofline_model.py",
    )
    try:
        spec = importlib.util.spec_from_file_location("simclr_roofline", path)
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        return float(
            sum(
                op[1]
                for op in module.model_step(
                    arch, per_device_batch, d=d, augment_impl=augment_impl
                )
            )
        )
    except Exception:
        return None


class Telemetry:
    """The run's metric registry; see module docstring. Usage::

        telemetry = Telemetry(arch="resnet18", per_device_batch=512, ...)
        telemetry.observe_epoch(epoch, loss=..., lr=..., steps=..., seconds=...)
        text = telemetry.render()        # the /metrics payload
        beat = telemetry.snapshot()      # the heartbeat.json enrichment

    ``flops_per_step`` applies to the PRETRAIN step shape (two views +
    NT-Xent + LARS); the supervised entry point passes ``arch=None`` so its
    MFU gauge honestly reads 0 instead of borrowing the wrong model.
    """

    def __init__(
        self,
        *,
        arch: str | None,
        per_device_batch: int,
        global_batch: int,
        n_devices: int,
        mesh_hosts: int = 1,
        d: int = 128,
        grad_allreduce: str = "exact",
        grad_elements: int | None = None,
        allreduce_devices: int | None = None,
        augment_impl: str = "xla",
        comm_overlap: str = "off",
        comm_chunks: int = 1,
        peak_flops: float = PEAK_FLOPS,
    ):
        self.global_batch = int(global_batch)
        self.n_devices = max(int(n_devices), 1)
        self.peak_flops = float(peak_flops)
        self.flops_per_step = (
            _roofline_flops_per_step(arch, per_device_batch, d, augment_impl)
            if arch else None
        )
        self._lock = threading.Lock()

        self.step_time = Histogram(
            "simclr_train_step_time_seconds",
            "Mean step wall time, observed once per epoch from the host loop",
            STEP_TIME_BUCKETS,
        )
        self.imgs_per_sec = Gauge(
            "simclr_train_imgs_per_sec",
            "Training throughput over the last epoch (dataset images/s)")
        self.imgs_per_sec_per_chip = Gauge(
            "simclr_train_imgs_per_sec_per_chip",
            "Per-device training throughput over the last epoch")
        self.mfu = Gauge(
            "simclr_train_mfu",
            "Model FLOPs utilization vs the bf16 peak, from the roofline "
            "FLOP model (scripts/roofline_model.py; 0 when no model applies)")
        self.loss = Gauge(
            "simclr_train_loss", "Epoch-mean training loss (last epoch)")
        self.lr = Gauge(
            "simclr_train_lr", "Learning rate at the last completed step")
        self.epoch = Gauge(
            "simclr_train_epoch", "Last completed epoch")
        self.epochs_total = Gauge(
            "simclr_train_epochs_total", "Configured total epochs for the run")
        self.step = Gauge(
            "simclr_train_step", "Last completed optimizer step")
        self.val_acc = Gauge(
            "simclr_train_val_acc",
            "Latest validation/monitor-probe accuracy (0 until first probe)")
        self.allreduce_wire_bytes = Gauge(
            "simclr_train_grad_allreduce_wire_bytes",
            "Analytic per-device wire bytes of one gradient all-reduce "
            "(parallel/compress.py)")
        self.checkpoint_save_seconds = Summary(
            "simclr_train_checkpoint_save_seconds",
            "Checkpoint save duration (excluded from throughput windows)")
        self.checkpoint_restore_seconds = Summary(
            "simclr_train_checkpoint_restore_seconds",
            "Checkpoint restore duration (resume and NaN rollback)")
        self.checkpoint_saves = Counter(
            "simclr_train_checkpoint_saves_total", "Checkpoints saved")
        self.nan_rollbacks = Counter(
            "simclr_train_nan_rollbacks_total",
            "Non-finite-loss rollbacks booked against the retry budget")
        self.anomaly_slow_steps = Counter(
            "simclr_train_anomaly_slow_steps_total",
            "Steps classified slow by the rolling median/MAD detector "
            "(obs/anomaly.py)")
        self.anomaly_stalls = Counter(
            "simclr_train_anomaly_stalls_total",
            "Stall-watchdog firings: no step completed within the armed "
            "deadline")
        self.auto_traces = Counter(
            "simclr_train_auto_traces_total",
            "Automatic profiler captures fired by the anomaly detector")
        self.scrape_disconnects = Counter(
            "simclr_train_scrape_disconnects_total",
            "Scrape responses dropped mid-write by a disconnecting peer")
        self.compiles = Counter(
            "simclr_train_compiles_total",
            "XLA compilations recorded by the compile sentry (obs/compile.py)")
        self.compile_seconds = Summary(
            "simclr_train_compile_seconds",
            "Wall time of each recorded XLA lower+compile")
        self.recompile_alarms = Counter(
            "simclr_train_recompile_alarms_total",
            "Post-warmup recompilations of a watched step function — the "
            "silent TPU perf killer")
        self.mesh_hosts = Gauge(
            "simclr_train_mesh_hosts",
            "Host processes backing the current mesh — drops on an elastic "
            "remesh-down, recovers on grow-back (parallel/mesh.py "
            "mesh_host_count)")
        self.mesh_hosts.set(float(max(int(mesh_hosts), 1)))
        self.mfu_xla_drift = Gauge(
            "simclr_train_mfu_roofline_xla_drift",
            "Fractional drift of the roofline FLOP model feeding the live "
            "MFU gauge vs XLA's analytic cost for the step executable "
            "(roofline/xla - 1; 0 until a step cost is recorded)")
        self.exposed_comm_ms = Gauge(
            "simclr_train_exposed_comm_ms",
            "Step wall time in excess of the roofline compute time, in ms — "
            "the communication the scheduler did NOT hide (0 when no roofline "
            "model applies; compare across comm_overlap=off|chunked|async)")
        self.grad_allreduce_mode = str(grad_allreduce)
        self.comm_overlap = str(comm_overlap)
        self.comm_chunks = int(comm_chunks)
        # name -> (flops/step, bytes/step) from the compile sentry, rendered
        # as labeled per-executable cost gauges
        self._xla_costs: dict[str, tuple[float, float]] = {}
        self._device_monitor = None
        if grad_elements:
            from simclr_tpu.parallel.compress import allreduce_wire_bytes

            # the gradient all-reduce spans the DATA axis, not the full mesh
            self.allreduce_wire_bytes.set(
                allreduce_wire_bytes(
                    int(grad_elements),
                    allreduce_devices or self.n_devices,
                    self.grad_allreduce_mode,
                    overlap=self.comm_overlap,
                    chunks=self.comm_chunks,
                )
            )
        self._metrics = (
            self.step_time, self.imgs_per_sec, self.imgs_per_sec_per_chip,
            self.mfu, self.loss, self.lr, self.epoch, self.epochs_total,
            self.step, self.val_acc, self.allreduce_wire_bytes,
            self.checkpoint_save_seconds, self.checkpoint_restore_seconds,
            self.checkpoint_saves, self.nan_rollbacks,
            self.anomaly_slow_steps, self.anomaly_stalls, self.auto_traces,
            self.scrape_disconnects, self.compiles, self.compile_seconds,
            self.recompile_alarms, self.mesh_hosts, self.mfu_xla_drift,
            self.exposed_comm_ms,
        )
        self._started = time.time()
        self._last_step_time = 0.0

    def attach_device_monitor(self, monitor) -> None:
        """Render the DeviceMonitor's HBM gauges with every scrape.

        Sampling happens inside :meth:`render`, i.e. on the exporter's
        handler thread — host-side ``memory_stats`` queries, zero device
        syncs (the monitor's contract, see obs/device.py).
        """
        self._device_monitor = monitor

    # -- update hooks (host floats only; no device values) -----------------
    def observe_epoch(
        self,
        epoch: int,
        *,
        epochs: int,
        step: int,
        steps: int,
        seconds: float,
        loss: float,
        lr: float,
    ) -> None:
        """Once per completed epoch: ``steps`` host-loop steps took
        ``seconds`` of wall clock (non-step work like eval/saves excluded by
        the caller's timer pauses where it matters). Works identically for
        per-step and ``epoch_compile`` loops — both know the epoch's step
        count and duration without extra syncs."""
        self.epoch.set(float(epoch))
        self.epochs_total.set(float(epochs))
        self.step.set(float(step))
        self.loss.set(float(loss))
        self.lr.set(float(lr))
        steps = max(int(steps), 1)
        seconds = max(float(seconds), 1e-9)
        step_time = seconds / steps
        self.step_time.observe(step_time)
        self._last_step_time = step_time
        rate = steps * self.global_batch / seconds
        self.imgs_per_sec.set(rate)
        self.imgs_per_sec_per_chip.set(rate / self.n_devices)
        if self.flops_per_step:
            self.mfu.set(self.flops_per_step / (step_time * self.peak_flops))
            # what the step spent beyond roofline compute: at 100% overlap
            # this tends to 0, and the off->chunked->async deltas attribute
            # exactly how much of the ring the scheduler hid
            self.exposed_comm_ms.set(
                max(0.0, step_time - self.flops_per_step / self.peak_flops)
                * 1000.0
            )

    def observe_save(self, seconds: float) -> None:
        self.checkpoint_save_seconds.observe(float(seconds))
        self.checkpoint_saves.inc()

    def observe_restore(self, seconds: float) -> None:
        self.checkpoint_restore_seconds.observe(float(seconds))

    def observe_val_acc(self, acc: float) -> None:
        self.val_acc.set(float(acc))

    def record_nan_rollback(self) -> None:
        self.nan_rollbacks.inc()

    def record_slow_step(self) -> None:
        self.anomaly_slow_steps.inc()

    def record_stall(self) -> None:
        self.anomaly_stalls.inc()

    def record_auto_trace(self) -> None:
        self.auto_traces.inc()

    def record_scrape_disconnect(self) -> None:
        self.scrape_disconnects.inc()

    def record_compile(self, seconds: float) -> None:
        self.compiles.inc()
        self.compile_seconds.observe(float(seconds))

    def record_recompile_alarm(self) -> None:
        self.recompile_alarms.inc()

    def observe_xla_cost(
        self, name: str, *, flops_per_step: float = 0.0,
        bytes_per_step: float = 0.0,
    ) -> None:
        """Per-executable analytic cost from the compile sentry.

        When the roofline FLOP model applies (pretrain), the drift gauge
        reconciles it against XLA's own analytic flops for the same step —
        the continuous version of the scripts/perf_attrib.py survey.
        """
        with self._lock:
            self._xla_costs[str(name)] = (
                float(flops_per_step), float(bytes_per_step)
            )
        if self.flops_per_step and flops_per_step > 0:
            self.mfu_xla_drift.set(self.flops_per_step / flops_per_step - 1.0)

    # -- read side ----------------------------------------------------------
    def snapshot(self) -> dict:
        """The compact latest-values dict riding on ``heartbeat.json`` (and
        surfaced by ``supervisor_summary.json``)."""
        return {
            "epoch": self.epoch.value,
            "step": self.step.value,
            "loss": self.loss.value,
            "lr": self.lr.value,
            # the FleetCollector's skew ratio divides these across hosts,
            # so the snapshot carries the scalar, not just the histogram
            "step_time_s": self._last_step_time,
            "imgs_per_sec": self.imgs_per_sec.value,
            "imgs_per_sec_per_chip": self.imgs_per_sec_per_chip.value,
            "mfu": self.mfu.value,
            "exposed_comm_ms": self.exposed_comm_ms.value,
            "slow_steps": self.anomaly_slow_steps.value,
            "stalls": self.anomaly_stalls.value,
            "auto_traces": self.auto_traces.value,
            "compiles": self.compiles.value,
            "recompile_alarms": self.recompile_alarms.value,
            "mesh_hosts": self.mesh_hosts.value,
            "uptime_s": round(time.time() - self._started, 3),
        }

    def render(self) -> str:
        parts = [m.render() for m in self._metrics]
        # mode as a labeled constant gauge — the Prometheus idiom for
        # categorical facts (like build_info)
        parts.append(
            "# HELP simclr_train_grad_allreduce_mode Wire format of the "
            "data-axis gradient all-reduce\n"
            "# TYPE simclr_train_grad_allreduce_mode gauge\n"
            f'simclr_train_grad_allreduce_mode{{mode="{self.grad_allreduce_mode}"}} 1\n'
        )
        with self._lock:
            costs = dict(self._xla_costs)
        if costs:
            flop_lines = "".join(
                f'simclr_train_xla_cost_flops{{executable="{name}"}} '
                f"{flops:g}\n"
                for name, (flops, _) in sorted(costs.items())
            )
            byte_lines = "".join(
                f'simclr_train_xla_cost_bytes_accessed{{executable="{name}"}} '
                f"{nbytes:g}\n"
                for name, (_, nbytes) in sorted(costs.items())
            )
            parts.append(
                "# HELP simclr_train_xla_cost_flops XLA analytic flops per "
                "step of each compiled executable (obs/compile.py)\n"
                "# TYPE simclr_train_xla_cost_flops gauge\n" + flop_lines
            )
            parts.append(
                "# HELP simclr_train_xla_cost_bytes_accessed XLA analytic "
                "bytes accessed per step of each compiled executable\n"
                "# TYPE simclr_train_xla_cost_bytes_accessed gauge\n"
                + byte_lines
            )
        if self._device_monitor is not None:
            # live HBM sampling happens here, on the scraping thread; a
            # backend hiccup must never break the whole /metrics payload
            try:
                parts.append(self._device_monitor.render())
            except Exception:
                pass
        return "".join(parts)
