"""Fleet observability plane: one merged view over every host and replica.

The per-process exporters (``obs/exporter.py``) answer "what is THIS host
doing"; this module answers "what is the FLEET doing". A
:class:`FleetCollector` runs as a daemon thread inside the supervisor
(both ``supervisor/runner.py`` and ``supervisor/elastic.py``), scrapes
every training host's ``/metrics`` + ``/healthz`` — discovered through the
per-process ready files ``telemetry.ready`` / ``telemetry.p<i>.ready``
(:func:`telemetry_ready_path`) — plus any serve-replica ``/metrics``
endpoints, and re-serves them merged on one HTTP endpoint:

  * ``GET /metrics``       — every host sample re-labeled
    ``simclr_train_X`` → ``simclr_fleet_X{host="N"}`` and every serve
    sample ``simclr_serve_X`` → ``simclr_fleet_serve_X{replica="N"}``,
    plus the derived fleet gauges below;
  * ``GET /fleet/healthz`` — the JSON fleet snapshot (also embedded into
    ``supervisor_summary.json`` at run end). ``/healthz`` is an alias.

Derived straggler gauges make a slow host visible BEFORE the wedge
watchdog fires:

  * ``simclr_fleet_step_time_skew_ratio`` — slowest/fastest per-host step
    time across hosts currently reporting (1 = perfectly even; SPMD makes
    every host wait for the slowest, so skew is pure waste);
  * ``simclr_fleet_slowest_host`` — the host index behind that ratio;
  * ``simclr_fleet_heartbeat_age_seconds{host="N"}`` — per-host liveness
    staleness from the ``heartbeat.p<i>.json`` files;
  * ``simclr_fleet_ready_file_missing/stale{host="N"}`` — a host whose
    ready file is gone (not started, or exited cleanly) or points at a
    dead port (killed without cleanup) is gauged, never raised on.

Scraping is read-only HTTP against exporters that render host-side floats
only, so the collector can never add a device sync to any training host —
the zero-sync contract holds fleet-wide by construction.

Stdlib-only by contract (plus ``supervisor.heartbeat``, itself stdlib):
the supervisor must never import jax.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlsplit

from simclr_tpu.supervisor.heartbeat import heartbeat_path, read_heartbeat

FLEET_READY_NAME = "fleet.ready"

_TRAIN_PREFIX = "simclr_train_"
_SERVE_PREFIX = "simclr_serve_"
_FLEET_PREFIX = "simclr_fleet_"


def telemetry_ready_path(ready_file: str, process_index: int = 0) -> str:
    """Per-process exporter ready file, mirroring ``heartbeat_path``.

    Process 0 keeps the configured path exactly (everything pre-fleet reads
    it); process ``i>0`` gets ``.p<i>`` spliced in before the final suffix —
    ``telemetry.ready`` → ``telemetry.p1.ready`` — so one configured path
    names the whole fleet's discovery files.
    """
    if not process_index:
        return ready_file
    head, tail = os.path.split(ready_file)
    stem, dot, suffix = tail.rpartition(".")
    if dot:
        tail = f"{stem}.p{int(process_index)}.{suffix}"
    else:
        tail = f"{tail}.p{int(process_index)}"
    return os.path.join(head, tail)


def _relabel_line(line: str, extra_label: str) -> tuple[str, str, str] | None:
    """Split one exposition sample line into (name, labels, value) with
    ``extra_label`` merged in front; None for comments/blank/garbage."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    try:
        metric, value = line.rsplit(None, 1)
    except ValueError:
        return None
    if "{" in metric:
        name, _, rest = metric.partition("{")
        labels = rest.rstrip("}")
        merged = f"{extra_label},{labels}" if labels else extra_label
    else:
        name, merged = metric, extra_label
    return name, merged, value


def _fleet_name(name: str, kind: str) -> str:
    """``simclr_train_X`` → ``simclr_fleet_X``; ``simclr_serve_X`` →
    ``simclr_fleet_serve_X``; anything else keeps its tail under the
    fleet prefix so the merged page has exactly one namespace."""
    if kind == "replica":
        if name.startswith(_SERVE_PREFIX):
            return _FLEET_PREFIX + "serve_" + name[len(_SERVE_PREFIX):]
        return _FLEET_PREFIX + "serve_" + name.removeprefix("simclr_")
    if name.startswith(_TRAIN_PREFIX):
        return _FLEET_PREFIX + name[len(_TRAIN_PREFIX):]
    return _FLEET_PREFIX + name.removeprefix("simclr_")


class _EndpointState:
    """Last-known scrape state for one host or replica endpoint."""

    def __init__(self):
        self.ready_missing = True
        self.ready_stale = False  # ready file present but scrape failed
        self.error: str | None = None
        self.metrics_text: str | None = None
        self.snapshot: dict | None = None
        self.scraped_at: float | None = None  # monotonic of last GOOD scrape

    @property
    def up(self) -> bool:
        return not self.ready_missing and not self.ready_stale


class FleetHTTPServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, address, collector: "FleetCollector"):
        super().__init__(address, FleetHandler)
        self.collector = collector


class FleetHandler(BaseHTTPRequestHandler):
    server: FleetHTTPServer

    def log_message(self, fmt, *args):  # noqa: D102
        pass

    def _send(self, code: int, body: bytes, content_type: str) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True

    def do_GET(self) -> None:  # noqa: N802
        path = urlsplit(self.path).path
        if path == "/metrics":
            self._send(
                200,
                self.server.collector.render().encode(),
                "text/plain; version=0.0.4",
            )
        elif path in ("/fleet/healthz", "/healthz"):
            self._send(
                200,
                json.dumps(self.server.collector.snapshot()).encode(),
                "application/json",
            )
        else:
            self._send(
                404,
                json.dumps({"error": f"unknown path {path!r}"}).encode(),
                "application/json",
            )


class FleetCollector:
    """Scrape every host/replica endpoint; merge, derive, re-serve.

    Tolerates absent children at every stage: a missing ready file, a ready
    file pointing at a dead port (the SIGKILLed host never ran ``close()``),
    a half-started exporter — each becomes a gauge on the fleet page, never
    an exception in the supervisor.
    """

    def __init__(
        self,
        save_dir: str,
        *,
        nprocs: int = 1,
        train_ready_file: str | None = None,
        serve_ready_files: tuple[str, ...] = (),
        poll_s: float = 2.0,
        stale_after_s: float = 30.0,
        timeout_s: float = 3.0,
        host: str = "127.0.0.1",
        port: int = 0,
        ready_file: str | None = None,
    ):
        self.save_dir = save_dir
        self.nprocs = int(nprocs)
        self.train_ready_file = train_ready_file
        # explicit listing (telemetry.fleet_serve_ready_files) plus any
        # serve*.ready file that appears in the run dir later — co-scheduled
        # serve replicas are discovered automatically each scrape pass
        self.serve_ready_files = list(serve_ready_files)
        self.poll_s = float(poll_s)
        self.stale_after_s = float(stale_after_s)
        self.timeout_s = float(timeout_s)
        self.ready_file = str(ready_file) if ready_file else None

        self._hosts: dict[int, _EndpointState] = {
            i: _EndpointState() for i in range(self.nprocs)
        }
        self._replicas: dict[int, _EndpointState] = {
            i: _EndpointState() for i in range(len(self.serve_ready_files))
        }
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._scrapes = 0
        self._scrape_errors = 0

        self._server = FleetHTTPServer((host, int(port)), self)
        self.host, self.port = self._server.server_address[:2]
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.2},
            name="fleet-collector-http",
            daemon=True,
        )
        self._serve_thread.start()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="fleet-collector-poll", daemon=True
        )
        self._poll_thread.start()
        if self.ready_file:
            from simclr_tpu.utils.ioutil import atomic_write

            # the supervisor starts the collector before any child has
            # created the run directory
            os.makedirs(os.path.dirname(self.ready_file) or ".", exist_ok=True)
            atomic_write(
                self.ready_file,
                lambda f: json.dump(
                    {"host": self.host, "port": self.port, "pid": os.getpid()},
                    f,
                ),
            )

    # -- scraping -----------------------------------------------------------

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            self.scrape_once()
            self._stop.wait(self.poll_s)

    def _read_ready(self, path: str) -> dict | None:
        try:
            with open(path) as f:
                info = json.load(f)
        except (OSError, ValueError):
            return None
        return info if isinstance(info, dict) and "port" in info else None

    def _fetch(self, addr: dict, path: str) -> str | None:
        url = f"http://{addr.get('host', '127.0.0.1')}:{addr['port']}{path}"
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            return resp.read().decode()

    def _scrape_endpoint(self, state: _EndpointState, ready_path: str | None,
                         *, want_snapshot: bool) -> None:
        if not ready_path:
            state.ready_missing = True
            return
        addr = self._read_ready(ready_path)
        if addr is None:
            # not started yet, or a clean exit removed it — gauge, don't raise
            state.ready_missing = True
            state.ready_stale = False
            state.error = None
            return
        state.ready_missing = False
        try:
            metrics = self._fetch(addr, "/metrics")
            snapshot = None
            if want_snapshot:
                body = self._fetch(addr, "/healthz")
                payload = json.loads(body) if body else None
                snapshot = payload if isinstance(payload, dict) else None
        except (urllib.error.URLError, OSError, ValueError,
                ConnectionError, TimeoutError) as e:
            # ready file present but nobody answering: a killed host left a
            # stale address behind
            state.ready_stale = True
            state.error = str(e)
            with self._lock:
                self._scrape_errors += 1
            return
        state.ready_stale = False
        state.error = None
        state.metrics_text = metrics
        if want_snapshot:
            state.snapshot = snapshot
        state.scraped_at = time.monotonic()

    def _discover_serve_ready(self) -> None:
        """Adopt any ``serve*.ready`` file in the run dir into the scrape
        set. Co-scheduled serve replicas publish their endpoints next to the
        train telemetry ready files, so the fleet view picks them up with
        no ``telemetry.fleet_serve_ready_files`` listing. Copy-on-write:
        snapshot/render threads iterate these structures concurrently."""
        try:
            names = sorted(os.listdir(self.save_dir))
        except OSError:
            return
        known = {os.path.abspath(p) for p in self.serve_ready_files}
        for name in names:
            if not (name.startswith("serve") and name.endswith(".ready")):
                continue
            path = os.path.join(self.save_dir, name)
            if os.path.abspath(path) in known:
                continue
            self.serve_ready_files = [*self.serve_ready_files, path]
            self._replicas = {
                **self._replicas,
                len(self.serve_ready_files) - 1: _EndpointState(),
            }

    def scrape_once(self) -> None:
        """One pass over every endpoint (also what the poll thread runs)."""
        self._discover_serve_ready()
        for rank, state in self._hosts.items():
            ready = (
                telemetry_ready_path(self.train_ready_file, rank)
                if self.train_ready_file
                else None
            )
            self._scrape_endpoint(state, ready, want_snapshot=True)
        files = self.serve_ready_files
        for idx, state in list(self._replicas.items()):
            self._scrape_endpoint(state, files[idx], want_snapshot=False)
        with self._lock:
            self._scrapes += 1

    # -- derived views ------------------------------------------------------

    def _step_times(self) -> dict[int, float]:
        out = {}
        for rank, state in self._hosts.items():
            snap = state.snapshot or {}
            try:
                step_time = float(snap.get("step_time_s"))
            except (TypeError, ValueError):
                continue
            if step_time > 0:
                out[rank] = step_time
        return out

    def _heartbeat_ages(self, now: float) -> dict[int, float | None]:
        ages: dict[int, float | None] = {}
        for rank in self._hosts:
            beat = read_heartbeat(heartbeat_path(self.save_dir, rank))
            when = beat.get("time") if beat else None
            ages[rank] = (
                round(max(0.0, now - when), 3)
                if isinstance(when, (int, float))
                else None
            )
        return ages

    def snapshot(self) -> dict:
        """The ``/fleet/healthz`` JSON — also what the supervisor summary
        embeds at run end."""
        now = time.time()
        mono = time.monotonic()
        step_times = self._step_times()
        ages = self._heartbeat_ages(now)
        skew, slowest = 0.0, None
        if step_times:
            slowest = max(step_times, key=step_times.get)
            skew = round(step_times[slowest] / min(step_times.values()), 4)
        hosts = {}
        for rank, state in self._hosts.items():
            snap = state.snapshot or {}
            hosts[str(rank)] = {
                "up": state.up,
                "ready_missing": state.ready_missing,
                "ready_stale": state.ready_stale,
                "error": state.error,
                "heartbeat_age_s": ages[rank],
                "scrape_age_s": (
                    round(mono - state.scraped_at, 3)
                    if state.scraped_at is not None
                    else None
                ),
                "step_time_s": step_times.get(rank),
                "step": snap.get("step"),
                "epoch": snap.get("epoch"),
                "imgs_per_sec": snap.get("imgs_per_sec"),
            }
        replicas = {
            str(idx): {
                "up": state.up,
                "ready_missing": state.ready_missing,
                "ready_stale": state.ready_stale,
                "error": state.error,
            }
            for idx, state in self._replicas.items()
        }
        with self._lock:
            scrapes, errors = self._scrapes, self._scrape_errors
        return {
            "status": "ok",
            "hosts_expected": self.nprocs,
            "hosts_up": sum(1 for s in self._hosts.values() if s.up),
            "replicas_expected": len(self._replicas),
            "replicas_up": sum(1 for s in self._replicas.values() if s.up),
            "step_time_skew_ratio": skew,
            "slowest_host": slowest,
            "hosts": hosts,
            "replicas": replicas,
            "scrapes": scrapes,
            "scrape_errors": errors,
        }

    def render(self) -> str:
        """The merged ``/metrics`` page: derived fleet gauges first, then
        every host/replica sample re-labeled into the fleet namespace."""
        snap = self.snapshot()
        lines = [
            "# fleet: merged scrape of "
            f"{snap['hosts_expected']} host(s), "
            f"{snap['replicas_expected']} replica(s)",
            f"# TYPE {_FLEET_PREFIX}hosts_expected gauge",
            f"{_FLEET_PREFIX}hosts_expected {snap['hosts_expected']:g}",
            f"# TYPE {_FLEET_PREFIX}hosts_up gauge",
            f"{_FLEET_PREFIX}hosts_up {snap['hosts_up']:g}",
            f"# TYPE {_FLEET_PREFIX}replicas_up gauge",
            f"{_FLEET_PREFIX}replicas_up {snap['replicas_up']:g}",
            f"# TYPE {_FLEET_PREFIX}step_time_skew_ratio gauge",
            f"{_FLEET_PREFIX}step_time_skew_ratio "
            f"{snap['step_time_skew_ratio']:g}",
            f"# TYPE {_FLEET_PREFIX}scrapes_total counter",
            f"{_FLEET_PREFIX}scrapes_total {snap['scrapes']:g}",
            f"# TYPE {_FLEET_PREFIX}scrape_errors_total counter",
            f"{_FLEET_PREFIX}scrape_errors_total {snap['scrape_errors']:g}",
        ]
        if snap["slowest_host"] is not None:
            lines.append(f"# TYPE {_FLEET_PREFIX}slowest_host gauge")
            lines.append(
                f"{_FLEET_PREFIX}slowest_host {snap['slowest_host']:g}"
            )
        for rank_str, info in snap["hosts"].items():
            label = f'host="{rank_str}"'
            lines.append(
                f"{_FLEET_PREFIX}host_up{{{label}}} {int(info['up']):g}"
            )
            lines.append(
                f"{_FLEET_PREFIX}ready_file_missing{{{label}}} "
                f"{int(info['ready_missing']):g}"
            )
            lines.append(
                f"{_FLEET_PREFIX}ready_file_stale{{{label}}} "
                f"{int(info['ready_stale']):g}"
            )
            if info["heartbeat_age_s"] is not None:
                lines.append(
                    f"{_FLEET_PREFIX}heartbeat_age_seconds{{{label}}} "
                    f"{info['heartbeat_age_s']:g}"
                )
            if info["step_time_s"] is not None:
                lines.append(
                    f"{_FLEET_PREFIX}host_step_time_seconds{{{label}}} "
                    f"{info['step_time_s']:g}"
                )
        for rank, state in self._hosts.items():
            if not state.metrics_text:
                continue
            extra = f'host="{rank}"'
            for line in state.metrics_text.splitlines():
                parsed = _relabel_line(line, extra)
                if parsed is None:
                    continue
                name, labels, value = parsed
                lines.append(
                    f"{_fleet_name(name, 'host')}{{{labels}}} {value}"
                )
        for idx, state in self._replicas.items():
            if not state.metrics_text:
                continue
            extra = f'replica="{idx}"'
            for line in state.metrics_text.splitlines():
                parsed = _relabel_line(line, extra)
                if parsed is None:
                    continue
                name, labels, value = parsed
                lines.append(
                    f"{_fleet_name(name, 'replica')}{{{labels}}} {value}"
                )
        return "\n".join(lines) + "\n"

    def close(self) -> None:
        self._stop.set()
        self._poll_thread.join(timeout=5.0)
        self._server.shutdown()
        self._serve_thread.join(timeout=5.0)
        self._server.server_close()
        if self.ready_file:
            try:
                os.unlink(self.ready_file)
            except OSError:
                pass


def maybe_start_fleet(cfg, save_dir: str, *, nprocs: int = 1) -> FleetCollector | None:
    """Config gate for the supervisors: ``telemetry.fleet=true`` starts the
    collector (its ready file defaults to ``<save_dir>/fleet.ready``)."""
    if not cfg.select("telemetry.fleet", False):
        return None
    ready_file = cfg.select("telemetry.fleet_ready_file") or os.path.join(
        save_dir, FLEET_READY_NAME
    )
    serve_ready = cfg.select("telemetry.fleet_serve_ready_files")
    serve_ready_files = tuple(
        p.strip() for p in str(serve_ready).split(",") if p.strip()
    ) if serve_ready else ()
    return FleetCollector(
        save_dir,
        nprocs=nprocs,
        train_ready_file=cfg.select("telemetry.ready_file"),
        serve_ready_files=serve_ready_files,
        poll_s=float(cfg.select("telemetry.fleet_poll_s", 2.0)),
        stale_after_s=float(cfg.select("telemetry.fleet_stale_after_s", 30.0)),
        port=int(cfg.select("telemetry.fleet_port", 0) or 0),
        ready_file=ready_file,
    )
