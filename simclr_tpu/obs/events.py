"""Structured run timeline: one ``events.jsonl`` per run directory.

Every notable transition of a (possibly multi-restart) run lands as one
JSON line — ``run_start``, ``epoch``, ``checkpoint``, ``nan_rollback``,
``preempt``, ``resume`` from the training process, plus the supervisor
runner's ``child_exit``/``restart``/``hang``/``outcome`` — so a single file
reconstructs a kill -9 + auto-resume run end to end without correlating
logs across attempts.

Design contracts:

  * **atomic appends** — each line is one ``O_APPEND`` write
    (:func:`simclr_tpu.utils.ioutil.atomic_append`), so the training child
    and the supervisor parent can interleave writers without tearing lines;
  * **two clocks** — every event carries wall-clock ``time`` (cross-attempt
    ordering; attempts are processes with disjoint monotonic clocks) and
    ``monotonic`` (NTP-step-proof intervals within an attempt);
  * **attempt tagging** — the supervisor exports its attempt ordinal to the
    child (``SIMCLR_SUPERVISOR_ATTEMPT``, the same env the ``[attempt N]``
    log tag reads); the runner passes its own ordinal explicitly;
  * **resume re-seat** — a resume rewrites the file dropping ``epoch`` and
    ``checkpoint`` events the restarted run is about to re-emit (epoch >=
    the resume point), the same discipline as ``pretrain_results.json``.
    Forensic events (``preempt``, ``nan_rollback``, ``child_exit``) are
    never dropped — they are what happened, not what will be recomputed.

Stdlib-only by contract: the supervisor runner writes events without
touching jax.
"""

from __future__ import annotations

import json
import os
import time

from simclr_tpu.utils.ioutil import atomic_append, atomic_write

EVENTS_NAME = "events.jsonl"

# the attempt ordinal env var; duplicated from supervisor/runner.py rather
# than imported so this module stays importable without the supervisor
ENV_ATTEMPT = "SIMCLR_SUPERVISOR_ATTEMPT"

# event types a resume re-seat drops at/past the resume epoch: the restarted
# run deterministically re-runs those epochs and re-emits both
RESEAT_TYPES = ("epoch", "checkpoint")


def events_path(save_dir: str) -> str:
    """The run's event timeline, fixed relative to ``save_dir`` (like
    ``heartbeat.json``) so every writer finds it with no channel but argv."""
    return os.path.join(save_dir, EVENTS_NAME)


def read_events_counted(path: str) -> tuple[list[dict], int]:
    """Parse the timeline, counting unparseable lines instead of hiding
    them. A SIGKILL can tear at most the final line — ``O_APPEND`` writes
    keep whole lines atomic on local filesystems, but the reader stays
    defensive — and the count lets ``obs/report.py`` flag a truncated
    timeline instead of silently under-reporting. Returns
    ``(events, skipped_lines)``; ``([], 0)`` when the file is absent."""
    events: list[dict] = []
    skipped = 0
    try:
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    payload = json.loads(line)
                except ValueError:
                    skipped += 1
                    continue
                if isinstance(payload, dict):
                    events.append(payload)
                else:
                    skipped += 1
    except OSError:
        return [], 0
    return events, skipped


def read_events(path: str) -> list[dict]:
    """:func:`read_events_counted` for callers that only want the events."""
    return read_events_counted(path)[0]


class EventLog:
    """Append-only writer for one run's ``events.jsonl``.

    Constructed per process; ``enabled=False`` (the ``telemetry.events``
    knob, or a non-logging host) turns every method into a no-op so call
    sites stay unconditional.
    """

    def __init__(
        self,
        save_dir: str,
        *,
        enabled: bool = True,
        attempt: int | None = None,
    ):
        self.path = events_path(save_dir)
        self.enabled = bool(enabled)
        if attempt is None:
            try:
                attempt = int(os.environ.get(ENV_ATTEMPT, "1"))
            except ValueError:
                attempt = 1
        self.attempt = attempt

    def emit(self, event: str, **fields) -> None:
        """Append one event line. Explicit ``fields`` win over the defaults,
        so the supervisor runner can stamp the attempt that just exited
        rather than its own (always-1) environment."""
        if not self.enabled:
            return
        payload = {
            "event": event,
            "time": time.time(),
            "monotonic": time.monotonic(),
            "attempt": self.attempt,
        }
        payload.update(fields)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        atomic_append(self.path, json.dumps(payload) + "\n")

    def reseat(self, start_epoch: int) -> None:
        """Drop re-runnable events (:data:`RESEAT_TYPES`) at or past the
        resume epoch, keeping everything earlier plus all forensic events —
        the exact analogue of the ``pretrain_results.json`` re-seat, so a
        resumed run appends without duplicating epoch rows. Unparseable
        (torn) lines are dropped with the rewrite."""
        if not self.enabled or not os.path.exists(self.path):
            return
        kept = [
            e
            for e in read_events(self.path)
            if not (
                e.get("event") in RESEAT_TYPES
                and isinstance(e.get("epoch"), (int, float))
                and e["epoch"] >= start_epoch
            )
        ]
        atomic_write(
            self.path,
            lambda f: f.writelines(json.dumps(e) + "\n" for e in kept),
        )
