"""Cross-host run timeline as Chrome/Perfetto trace-event JSON.

    python -m simclr_tpu.obs.timeline <run_dir> [-o trace.json]

Merges everything a run directory records about time — the
``events.jsonl`` stream (training epochs/checkpoints, supervisor
lifecycle, elastic ``host_lost``/``remesh``/``grow_back``), the per-host
``heartbeat.p<i>.json`` files, and ``supervisor_summary.json`` — into one
trace-event file that ``chrome://tracing`` or https://ui.perfetto.dev
renders as tracks:

  * one track (``pid``) per host slot, with epoch spans (``ph="X"``,
    duration from the event's ``seconds`` field) and instant markers for
    checkpoints, stalls, auto-traces, compiles and the host's last
    heartbeat. Trainer-emitted events come from the generation's logging
    host and are attributed to slot 0 (the lowest slot survives every
    fixture remesh and re-elects as rank 0);
  * a supervisor track carrying ``run_start``/``child_exit``/``restart``/
    ``remesh 2→1``/``grow_back``/``outcome`` lifecycle markers;
  * a serve track for ``serve_*`` events (e.g. a ``serve_swap`` span when
    the serving tier swaps weights mid-run).

Within one track the ``tid`` is the attempt (supervisor restart ordinal or
elastic generation), so attempts stack as separate rows under each host.
Timestamps are wall-clock microseconds rebased to the run's first event,
emitted sorted so every track is monotonic.

Stdlib-only by contract (plus ``obs.events`` + ``supervisor.heartbeat``,
both stdlib): the timeline renders anywhere the run directory is mounted.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

from simclr_tpu.obs.events import events_path, read_events_counted
from simclr_tpu.supervisor.heartbeat import HEARTBEAT_NAME, read_heartbeat

TRACE_NAME = "timeline_trace.json"

# pid blocks: trace viewers group rows by pid, so each logical track gets
# a disjoint small integer
PID_SUPERVISOR = 1
PID_SERVE = 2
PID_HOST_BASE = 10  # host slot i renders as pid 10 + i

# supervisor/lifecycle event kinds (everything the trainers do NOT emit)
_LIFECYCLE = {
    "run_start", "run_end", "outcome", "child_exit", "restart", "hang",
    "remesh", "grow_back", "topology_change", "reallocate",
}

# co-scheduler serve-plane kinds that don't carry the "serve" prefix
_SERVE_EVENTS = {"swap", "swap_rejected"}


def _num(value, default=None):
    return value if isinstance(value, (int, float)) else default


def _attempt(event: dict) -> int:
    try:
        return int(event.get("attempt", 1))
    except (TypeError, ValueError):
        return 1


def _event_name(event: dict) -> str:
    kind = event.get("event", "?")
    if kind == "epoch":
        return f"epoch {event.get('epoch', '?')}"
    if kind == "checkpoint":
        return f"checkpoint e{event.get('epoch', '?')}"
    if kind == "remesh":
        return (
            f"remesh {event.get('hosts_before', '?')}"
            f"→{event.get('hosts_after', '?')}"
        )
    if kind == "host_lost":
        return f"host_lost ({event.get('reason', '?')})"
    if kind == "grow_back":
        hosts = event.get("hosts")
        return f"grow_back {hosts}" if hosts else "grow_back"
    if kind == "outcome":
        return f"outcome: {event.get('outcome', '?')}"
    if kind == "swap":
        return f"swap e{event.get('epoch', '?')} → gen {event.get('generation', '?')}"
    if kind == "swap_rejected":
        return f"swap_rejected e{event.get('epoch', '?')}"
    if kind == "reallocate":
        return f"reallocate ({event.get('direction', '?')})"
    return str(kind)


def _track_for(event: dict) -> int:
    """Which pid an event renders under (see module doc)."""
    kind = str(event.get("event", ""))
    if kind == "host_lost" and _num(event.get("host")) is not None:
        return PID_HOST_BASE + int(event["host"])
    if kind.startswith("serve") or kind in _SERVE_EVENTS:
        return PID_SERVE
    if kind in _LIFECYCLE:
        return PID_SUPERVISOR
    # trainer-emitted: the generation's logging host, attributed to slot 0
    return PID_HOST_BASE + 0


def _host_slots(events: list[dict], run_dir: str) -> list[int]:
    """Every host slot the run ever touched: remesh host counts, explicit
    per-event host fields, grow_back lists, and heartbeat.p<i>.json files."""
    slots = {0}
    for event in events:
        for key in ("hosts_before", "hosts_after"):
            count = _num(event.get(key))
            if count is not None:
                slots.update(range(int(count)))
        host = _num(event.get("host"))
        if host is not None:
            slots.add(int(host))
        hosts = event.get("hosts")
        if isinstance(hosts, list):
            slots.update(int(h) for h in hosts if isinstance(h, int))
    for path in glob.glob(os.path.join(run_dir, "heartbeat*.json")):
        match = re.search(r"heartbeat\.p(\d+)\.json$", path)
        if match:
            slots.add(int(match.group(1)))
        elif os.path.basename(path) == HEARTBEAT_NAME:
            slots.add(0)
    return sorted(slots)


def build_timeline(run_dir: str) -> dict:
    """The trace-event document for one run directory.

    Always returns a valid (possibly near-empty) document; ``torn_lines``
    in ``otherData`` counts unparseable event lines that were skipped.
    """
    events, torn = read_events_counted(events_path(run_dir))
    timed = [e for e in events if _num(e.get("time")) is not None]
    slots = _host_slots(events, run_dir)

    heartbeats: dict[int, dict] = {}
    for slot in slots:
        name = HEARTBEAT_NAME if slot == 0 else f"heartbeat.p{slot}.json"
        beat = read_heartbeat(os.path.join(run_dir, name))
        if beat is not None and _num(beat.get("time")) is not None:
            heartbeats[slot] = beat

    base_candidates = [e["time"] for e in timed]
    base_candidates += [b["time"] for b in heartbeats.values()]
    base = min(base_candidates) if base_candidates else 0.0

    def us(when: float) -> int:
        return max(0, int(round((when - base) * 1e6)))

    trace: list[dict] = []
    # process_name metadata rows label the tracks in the viewer
    names = {PID_SUPERVISOR: "supervisor", PID_SERVE: "serve"}
    names.update({PID_HOST_BASE + s: f"host {s}" for s in slots})
    for pid, label in sorted(names.items()):
        trace.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": label},
        })

    body: list[dict] = []
    for event in timed:
        pid = _track_for(event)
        tid = _attempt(event)
        seconds = _num(event.get("seconds"))
        args = {
            k: v
            for k, v in event.items()
            if k not in ("event", "time", "monotonic") and v is not None
        }
        if seconds is not None and seconds > 0:
            # a span whose duration the event recorded (epoch, compile):
            # the event is stamped at the END of the interval
            body.append({
                "ph": "X", "name": _event_name(event), "pid": pid,
                "tid": tid, "ts": us(event["time"] - seconds),
                "dur": int(round(seconds * 1e6)), "args": args,
            })
        else:
            body.append({
                "ph": "i", "s": "t", "name": _event_name(event), "pid": pid,
                "tid": tid, "ts": us(event["time"]), "args": args,
            })
    for slot, beat in heartbeats.items():
        body.append({
            "ph": "i", "s": "t", "name": "last_heartbeat",
            "pid": PID_HOST_BASE + slot, "tid": _attempt(beat),
            "ts": us(beat["time"]),
            "args": {
                k: beat.get(k)
                for k in ("step", "epoch", "status")
                if beat.get(k) is not None
            },
        })
    body.sort(key=lambda e: (e["ts"], e["pid"], e["tid"]))
    trace.extend(body)

    summary = None
    try:
        with open(os.path.join(run_dir, "supervisor_summary.json")) as f:
            payload = json.load(f)
        summary = payload if isinstance(payload, dict) else None
    except (OSError, ValueError):
        pass

    other = {"run_dir": os.path.abspath(run_dir), "torn_lines": torn}
    if summary is not None:
        for key in ("outcome", "remesh_count", "grow_back_count",
                    "hosts_timeline"):
            if key in summary:
                other[key] = summary[key]
    return {
        "traceEvents": trace,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def trace_path(run_dir: str) -> str:
    return os.path.join(run_dir, TRACE_NAME)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m simclr_tpu.obs.timeline",
        description="Merge a run directory's events/heartbeats into "
        "Chrome/Perfetto trace-event JSON (load at ui.perfetto.dev).",
    )
    parser.add_argument("run_dir", help="run save_dir holding events.jsonl")
    parser.add_argument(
        "-o", "--out", default=None,
        help=f"output path (default <run_dir>/{TRACE_NAME})",
    )
    args = parser.parse_args(argv)

    document = build_timeline(args.run_dir)
    out = args.out or trace_path(args.run_dir)
    with open(out, "w", encoding="utf-8") as f:
        json.dump(document, f)
        f.write("\n")
    spans = sum(1 for e in document["traceEvents"] if e["ph"] != "M")
    torn = document["otherData"]["torn_lines"]
    torn_part = f" ({torn} torn line(s) skipped)" if torn else ""
    print(f"timeline: {spans} events -> {out}{torn_part}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
