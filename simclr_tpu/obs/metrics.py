"""Metric primitives rendered in the Prometheus text exposition format.

Minimal, dependency-free instrumentation shared by the serving tier
(``serve/metrics.py`` re-exports these unchanged) and the training-side
telemetry registry (``obs/telemetry.py``). Four primitives:

  * :class:`Counter` — monotonically increasing totals (requests, rows,
    rejections, batches, compile-cache hits/misses, NaN rollbacks);
  * :class:`Gauge` — point-in-time values, either set explicitly or read
    from a callback at render time (queue depth);
  * :class:`Summary` — streaming latency quantiles (p50/p95/p99) over a
    bounded reservoir of recent observations, plus exact ``_sum``/``_count``;
  * :class:`Histogram` — fixed cumulative buckets with exact counts, for
    distributions where a dashboard wants ``histogram_quantile`` over time
    windows (step time) rather than a process-lifetime reservoir.

Everything is thread-safe: handler threads record, the batcher worker
records, the training loop records, and ``/metrics`` renders — all
concurrently. This module is stdlib-only by contract: the supervisor
runner and the serve tier import it without paying for jax.
"""

from __future__ import annotations

import bisect
import threading
from collections import deque
from typing import Callable, Sequence


class Counter:
    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def render(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n"
            f"# TYPE {self.name} counter\n"
            f"{self.name} {self.value:g}\n"
        )


class Gauge:
    """Explicit ``set()`` or a zero-arg callback sampled at render time."""

    def __init__(self, name: str, help_text: str, fn: Callable[[], float] | None = None):
        self.name = name
        self.help = help_text
        self._fn = fn
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value

    def set_fn(self, fn: Callable[[], float]) -> None:
        """Bind a live source sampled at render time (e.g. queue.qsize)."""
        self._fn = fn

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # callback target may be mid-shutdown
                return 0.0
        with self._lock:
            return self._value

    def render(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n"
            f"# TYPE {self.name} gauge\n"
            f"{self.name} {self.value:g}\n"
        )


class Summary:
    """Quantiles over a sliding reservoir of the most recent observations.

    ``_sum``/``_count`` are exact over the full history; the p50/p95/p99
    quantile lines are computed from the last ``reservoir`` observations —
    recent-window percentiles are what a serving dashboard wants (steady
    state, not startup-compile transients). Quantiles are linear
    interpolations over the sorted reservoir, NaN when empty (the
    Prometheus convention for unobserved summaries).
    """

    QUANTILES = (0.5, 0.95, 0.99)

    def __init__(self, name: str, help_text: str, reservoir: int = 2048):
        self.name = name
        self.help = help_text
        self._samples: deque[float] = deque(maxlen=reservoir)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._samples.append(float(value))
            self._sum += float(value)
            self._count += 1

    def quantile(self, q: float) -> float:
        with self._lock:
            data = sorted(self._samples)
        if not data:
            return float("nan")
        pos = q * (len(data) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(data) - 1)
        return data[lo] + (data[hi] - data[lo]) * (pos - lo)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} summary",
        ]
        for q in self.QUANTILES:
            lines.append(f'{self.name}{{quantile="{q:g}"}} {self.quantile(q):g}')
        lines.append(f"{self.name}_sum {self.sum:g}")
        lines.append(f"{self.name}_count {self.count:g}")
        return "\n".join(lines) + "\n"


class Histogram:
    """Fixed-bucket cumulative histogram (the Prometheus ``histogram`` type).

    Where :class:`Summary` answers "what are the recent percentiles", a
    histogram's exact per-bucket counts let a scraper compute quantiles over
    ANY time window (``histogram_quantile(rate(..._bucket[5m]))``) and merge
    across restarts — the right shape for step-time distributions on runs
    that live for days. Buckets are upper bounds, sorted ascending; an
    implicit ``+Inf`` bucket catches everything beyond the last bound.
    """

    def __init__(self, name: str, help_text: str, buckets: Sequence[float]):
        if not buckets:
            raise ValueError(f"histogram {name}: at least one bucket bound required")
        self.name = name
        self.help = help_text
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # one slot per finite bucket plus the +Inf overflow slot; rendered
        # cumulatively, stored per-bucket so observe() is a single increment
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        slot = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[slot] += 1
            self._sum += value

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def render(self) -> str:
        with self._lock:
            counts = list(self._counts)
            total_sum = self._sum
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        cumulative = 0
        for bound, count in zip(self.buckets, counts):
            cumulative += count
            lines.append(f'{self.name}_bucket{{le="{bound:g}"}} {cumulative:g}')
        cumulative += counts[-1]
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {cumulative:g}')
        lines.append(f"{self.name}_sum {total_sum:g}")
        lines.append(f"{self.name}_count {cumulative:g}")
        return "\n".join(lines) + "\n"
