"""Request tracing: monotonic-clock spans with no dependencies.

The serve tier answers "why was THIS request slow" with a per-request
span breakdown instead of an aggregate histogram: every ``/v1/embed``
request carries a :class:`RequestTrace` through handler -> batcher ->
engine, collecting queue-wait / coalesce / pad / device-compute /
serialize spans stamped from ``time.perf_counter()``.  Completed traces
land in a :class:`TraceRecorder`, which keeps a bounded ring of the
slowest requests (served at ``GET /debug/slow``) and optionally samples
a deterministic fraction into a ``requests.jsonl`` sidecar.

Everything here is host-clock arithmetic on floats the serve path
already computes — tracing never touches a device value, so the
zero-sync discipline of ``docs/OBSERVABILITY.md`` holds with spans on.
"""

from __future__ import annotations

import heapq
import json
import threading
import time
import uuid

from simclr_tpu.utils.ioutil import atomic_append

# default depth of the slowest-requests ring at GET /debug/slow
SLOW_RING_CAPACITY = 32

_MAX_REQUEST_ID_LEN = 128


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


def clean_request_id(raw) -> str:
    """A usable request id: the client-supplied header value sanitized
    (printable, no whitespace, bounded length), or a fresh one."""
    if raw is not None:
        rid = "".join(
            c for c in str(raw).strip() if c.isprintable() and not c.isspace()
        )
        rid = rid[:_MAX_REQUEST_ID_LEN]
        if rid:
            return rid
    return new_request_id()


class RequestTrace:
    """Span collection for one request.

    Spans are ``(name, start, end)`` tuples in ``time.perf_counter()``
    seconds.  A trace crosses threads exactly once (handler -> batcher
    worker and back through the Future, which gives happens-before), but
    a lock keeps ``add`` safe regardless.
    """

    __slots__ = ("request_id", "t0", "_spans", "_lock")

    def __init__(self, request_id: str | None = None):
        self.request_id = request_id or new_request_id()
        self.t0 = time.perf_counter()
        self._spans: list[tuple[str, float, float]] = []
        self._lock = threading.Lock()

    def add(self, name: str, start: float, end: float) -> None:
        with self._lock:
            self._spans.append((str(name), float(start), float(end)))

    def span(self, name: str) -> "_SpanContext":
        """``with trace.span("serialize"): ...`` stamps one span."""
        return _SpanContext(self, name)

    def spans(self) -> list[tuple[str, float, float]]:
        with self._lock:
            return list(self._spans)

    def total_s(self) -> float:
        """Request start to the last span end (0 if no spans yet)."""
        end = max((e for _, _, e in self.spans()), default=self.t0)
        return end - self.t0

    def to_dict(self) -> dict:
        spans = self.spans()
        end = max((e for _, _, e in spans), default=self.t0)
        return {
            "request_id": self.request_id,
            "total_ms": round((end - self.t0) * 1000.0, 3),
            "spans": [
                {
                    "name": name,
                    "start_ms": round((start - self.t0) * 1000.0, 3),
                    "dur_ms": round((span_end - start) * 1000.0, 3),
                }
                for name, start, span_end in spans
            ],
        }


class _SpanContext:
    __slots__ = ("_trace", "_name", "_start")

    def __init__(self, trace: RequestTrace, name: str):
        self._trace = trace
        self._name = name

    def __enter__(self) -> "_SpanContext":
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._trace.add(self._name, self._start, time.perf_counter())


class TraceRecorder:
    """Terminal sink for completed traces.

    Keeps the ``capacity`` slowest traces in a min-heap (evict the
    fastest when full) for ``GET /debug/slow``, and — when ``path`` and
    ``sample_rate`` are set — appends every Nth completed trace as one
    JSON line.  Sampling uses a deterministic accumulator rather than a
    PRNG so a rate of 0.25 means exactly every 4th request, which keeps
    the sidecar's growth rate predictable.
    """

    def __init__(
        self,
        *,
        sample_rate: float = 0.0,
        path: str | None = None,
        capacity: int = SLOW_RING_CAPACITY,
    ):
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError(f"sample_rate must be in [0, 1], got {sample_rate}")
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.sample_rate = float(sample_rate)
        self.path = path
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        # (total_ms, seq, record): seq breaks ties so dicts never compare
        self._heap: list[tuple[float, int, dict]] = []
        self._seq = 0
        self._accum = 0.0

    def record(self, trace: RequestTrace) -> dict:
        rec = {"time": round(time.time(), 6), **trace.to_dict()}
        total_ms = rec["total_ms"]
        with self._lock:
            self._seq += 1
            heapq.heappush(self._heap, (total_ms, self._seq, rec))
            if len(self._heap) > self.capacity:
                heapq.heappop(self._heap)
            sampled = False
            if self.path and self.sample_rate > 0.0:
                self._accum += self.sample_rate
                if self._accum >= 1.0:
                    self._accum -= 1.0
                    sampled = True
        if sampled:
            atomic_append(self.path, json.dumps(rec) + "\n")
        return rec

    def slowest(self) -> list[dict]:
        """Retained traces, slowest first (most recent wins ties)."""
        with self._lock:
            items = sorted(self._heap, key=lambda t: (-t[0], -t[1]))
        return [rec for _, _, rec in items]
