"""Step-time anomaly detection: slow-step classifier, stall watchdog,
rate-limited automatic profiler capture.

The step-time histogram (PR 6) proves a latency tail existed; this
module answers *when* and records *why*.  The trainers tick a
:class:`StepAnomalyDetector` once per host step with nothing but
``time.monotonic()`` — zero extra device syncs:

* **slow_step** — a rolling median/MAD window over step durations
  classifies a step as slow when it exceeds
  ``median + slow_factor * MAD`` (MAD floored at 5% of the median so a
  perfectly steady stream, whose MAD is ~0, never flags ordinary
  jitter).  Emitted into ``events.jsonl`` and counted in ``/metrics``.
* **stall** — a wedged host loop cannot report its own absence, so a
  daemon watchdog thread is armed at every tick with a deadline of
  ``max(stall_min_s, stall_factor * median)``; if no tick (or
  :meth:`StepAnomalyDetector.pause`) lands in time, the watchdog emits a
  ``stall`` event from its own thread while the loop is still stuck —
  before the supervisor's heartbeat timeout SIGKILLs the process.
* **auto_trace** — both anomaly kinds can fire the existing
  ``capture_trace`` machinery (``utils/profiling.py``) so the chip's
  state at the moment of the anomaly is recorded with no operator
  present.  Captures run on a daemon thread (``capture_trace`` sleeps
  for the capture window), are rate-limited by a cooldown and a
  per-attempt budget, and land under ``<save_dir>/trace_auto/``.

Stdlib-only at import time; ``jax`` is imported lazily inside the
capture thread so the module stays usable from jax-free paths.
"""

from __future__ import annotations

import collections
import logging
import os
import statistics
import threading
import time

from simclr_tpu.obs.events import EventLog

logger = logging.getLogger("simclr_tpu")

SLOW_STEP_EVENT = "slow_step"
STALL_EVENT = "stall"
AUTO_TRACE_EVENT = "auto_trace"

# auto captures land in <save_dir>/trace_auto/trace-<unix>-<seq>/
AUTO_TRACE_DIR = "trace_auto"

# MAD floor: max(MAD, _MAD_FLOOR_FRAC * median, _MAD_FLOOR_ABS) keeps a
# constant-rate stream (MAD ~ 0) from flagging sub-percent jitter
_MAD_FLOOR_FRAC = 0.05
_MAD_FLOOR_ABS = 1e-4


class StepAnomalyDetector:
    """Rolling median/MAD slow-step classifier plus stall watchdog.

    ``tick()`` is called once per completed host step; ``pause()``
    before epoch-boundary work (probe, checkpoint I/O) so that gap is
    neither sampled as a step nor misread as a stall; ``close()`` once
    in the trainer's ``finally``.
    """

    def __init__(
        self,
        save_dir: str,
        *,
        telemetry=None,
        events: EventLog | None = None,
        window: int = 64,
        warmup: int = 8,
        slow_factor: float = 4.0,
        stall_factor: float = 10.0,
        stall_min_s: float = 2.0,
        auto_trace: bool = False,
        auto_trace_ms: float = 500.0,
        auto_trace_cooldown_s: float = 300.0,
        auto_trace_max: int = 3,
        capture_fn=None,
        clock=time.monotonic,
    ):
        self.save_dir = str(save_dir)
        self.telemetry = telemetry
        self.events = events
        self.warmup = max(2, int(warmup))
        self.slow_factor = float(slow_factor)
        self.stall_factor = float(stall_factor)
        self.stall_min_s = float(stall_min_s)
        self.auto_trace_enabled = bool(auto_trace)
        self.auto_trace_ms = float(auto_trace_ms)
        self.auto_trace_cooldown_s = float(auto_trace_cooldown_s)
        self.auto_trace_max = int(auto_trace_max)
        self._capture_fn = capture_fn
        self._clock = clock
        # the window must hold at least `warmup` samples or the detector
        # could never leave its grace period
        self._samples = collections.deque(maxlen=max(int(window), self.warmup))
        self._last_tick: float | None = None
        self._step = 0
        self._epoch = 0
        self.slow_steps = 0
        self.stalls = 0
        self.auto_traces = 0
        self._trace_lock = threading.Lock()
        self._traces_started = 0
        self._last_trace_at: float | None = None
        self._watchdog = _Watchdog(self._on_stall, clock=clock)

    # -- classification ------------------------------------------------

    def _stats(self):
        if len(self._samples) < self.warmup:
            return None, None
        med = statistics.median(self._samples)
        mad = statistics.median(abs(x - med) for x in self._samples)
        return med, mad

    def tick(self, step: int = 0, epoch: int = 0) -> str | None:
        """Record one completed step; returns ``"slow_step"`` when the
        step classified as anomalous, else None."""
        now = self._clock()
        self._step, self._epoch = int(step), int(epoch)
        verdict = None
        if self._last_tick is not None:
            dt = now - self._last_tick
            med, mad = self._stats()
            if med is not None:
                threshold = med + self.slow_factor * max(
                    mad, _MAD_FLOOR_FRAC * med, _MAD_FLOOR_ABS
                )
                if dt > threshold:
                    verdict = SLOW_STEP_EVENT
                    self.slow_steps += 1
                    if self.telemetry is not None:
                        self.telemetry.record_slow_step()
                    if self.events is not None:
                        self.events.emit(
                            SLOW_STEP_EVENT,
                            step=self._step,
                            epoch=self._epoch,
                            seconds=round(dt, 6),
                            median_s=round(med, 6),
                            threshold_s=round(threshold, 6),
                        )
                    logger.warning(
                        "slow step %d: %.3fs vs median %.3fs (threshold %.3fs)",
                        self._step,
                        dt,
                        med,
                        threshold,
                    )
                    self._maybe_auto_trace(SLOW_STEP_EVENT, dt)
            self._samples.append(dt)
        self._last_tick = now
        med, _ = self._stats()
        if med is not None:
            self._watchdog.arm(
                now + max(self.stall_min_s, self.stall_factor * med)
            )
        return verdict

    def pause(self) -> None:
        """Disarm across non-step work (probe, checkpoint, validation):
        the next tick re-anchors the clock without sampling the gap."""
        self._watchdog.disarm()
        self._last_tick = None

    def close(self) -> None:
        self._watchdog.close()

    # -- stall path (watchdog thread) ----------------------------------

    def _on_stall(self, armed_at: float) -> None:
        silence = self._clock() - armed_at
        self.stalls += 1
        if self.telemetry is not None:
            self.telemetry.record_stall()
        if self.events is not None:
            self.events.emit(
                STALL_EVENT,
                step=self._step,
                epoch=self._epoch,
                silence_s=round(silence, 3),
            )
        logger.warning(
            "stall: no step completed for %.1fs after step %d (epoch %d)",
            silence,
            self._step,
            self._epoch,
        )
        self._maybe_auto_trace(STALL_EVENT, silence)

    # -- automatic capture ---------------------------------------------

    def _maybe_auto_trace(self, reason: str, seconds: float) -> None:
        if not self.auto_trace_enabled:
            return
        now = self._clock()
        with self._trace_lock:
            if self._traces_started >= self.auto_trace_max:
                return
            if (
                self._last_trace_at is not None
                and now - self._last_trace_at < self.auto_trace_cooldown_s
            ):
                return
            self._traces_started += 1
            self._last_trace_at = now
            seq = self._traces_started
        trace_dir = os.path.join(
            self.save_dir, AUTO_TRACE_DIR, f"trace-{int(time.time())}-{seq:03d}"
        )
        # capture_trace sleeps for the whole window; never block the
        # caller (the train loop, or the watchdog that must stay alive)
        threading.Thread(
            target=self._capture,
            args=(trace_dir, reason, round(seconds, 3)),
            name="anomaly-auto-trace",
            daemon=True,
        ).start()

    def _capture(self, trace_dir: str, reason: str, seconds: float) -> None:
        capture = self._capture_fn
        if capture is None:
            from simclr_tpu.utils.profiling import capture_trace as capture
        try:
            os.makedirs(trace_dir, exist_ok=True)
            capture(trace_dir, self.auto_trace_ms / 1000.0)
        except Exception as exc:  # TraceInProgressError, profiler failures
            logger.warning("auto-trace (%s) failed: %s", reason, exc)
            return
        self.auto_traces += 1
        if self.telemetry is not None:
            self.telemetry.record_auto_trace()
        if self.events is not None:
            self.events.emit(
                AUTO_TRACE_EVENT,
                reason=reason,
                trigger_s=seconds,
                trace_dir=trace_dir,
                ms=self.auto_trace_ms,
                step=self._step,
                epoch=self._epoch,
            )
        logger.warning("auto-trace (%s) captured into %s", reason, trace_dir)


class _Watchdog:
    """Daemon thread that fires ``on_stall(armed_at)`` once per arming
    when the deadline passes without a re-arm or disarm."""

    def __init__(self, on_stall, clock=time.monotonic):
        self._on_stall = on_stall
        self._clock = clock
        self._cv = threading.Condition()
        self._deadline: float | None = None
        self._armed_at: float | None = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="step-watchdog", daemon=True
        )
        self._thread.start()

    def arm(self, deadline: float) -> None:
        with self._cv:
            self._deadline = deadline
            self._armed_at = self._clock()
            self._cv.notify()

    def disarm(self) -> None:
        with self._cv:
            self._deadline = None
            self._cv.notify()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify()
        self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    return
                if self._deadline is None:
                    self._cv.wait()
                    continue
                remaining = self._deadline - self._clock()
                if remaining > 0:
                    # a fake clock in tests never advances; the timed
                    # wait keeps this loop from spinning in that case
                    self._cv.wait(remaining)
                    continue
                armed_at = self._armed_at
                self._deadline = None  # fire once per arm
            self._on_stall(armed_at)


def maybe_detector(
    cfg, save_dir: str, *, telemetry=None, events=None
) -> StepAnomalyDetector | None:
    """Config-gated constructor used by the trainers (process 0 only)."""
    if not bool(cfg.select("telemetry.anomaly", True)):
        return None
    return StepAnomalyDetector(
        save_dir,
        telemetry=telemetry,
        events=events,
        warmup=int(cfg.select("telemetry.anomaly_warmup", 8)),
        slow_factor=float(cfg.select("telemetry.slow_step_factor", 4.0)),
        stall_factor=float(cfg.select("telemetry.stall_factor", 10.0)),
        stall_min_s=float(cfg.select("telemetry.stall_min_s", 2.0)),
        auto_trace=bool(cfg.select("telemetry.auto_trace", False)),
        auto_trace_ms=float(cfg.select("telemetry.auto_trace_ms", 500)),
        auto_trace_cooldown_s=float(
            cfg.select("telemetry.auto_trace_cooldown_s", 300.0)
        ),
        auto_trace_max=int(cfg.select("telemetry.auto_trace_max", 3)),
    )
