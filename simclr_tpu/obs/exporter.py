"""Per-host telemetry exporter: a daemon HTTP server beside the host loop.

Endpoints (docs/OBSERVABILITY.md):

  * ``GET /metrics``  — the :class:`~simclr_tpu.obs.telemetry.Telemetry`
    registry in Prometheus text format. Renders only host-side floats the
    loop already fetched — a scrape can NEVER add a device sync;
  * ``GET /healthz``  — ``{"status": "ok", ...snapshot}`` liveness JSON;
  * ``POST /debug/trace?ms=N`` — capture N ms of ``jax.profiler`` trace
    into ``<save_dir>/trace_on_demand/<stamp>/`` and return its path; the
    on-call answer to "what is the chip doing RIGHT NOW" without restarting
    the run with a profile window. Capped by ``telemetry.trace_max_ms``.

Address resolution mirrors the serve tier: ``telemetry.port`` picks a fixed
port; port 0 with ``telemetry.ready_file`` set binds an ephemeral port and
publishes ``{"host", "port", "pid"}`` to the ready file; port 0 with no
ready file means disabled (the default — a training run opens no sockets
unless asked). Handler threads are daemons so a wedged scraper can never
block the run's exit.

EVERY training process runs one of these, not just process 0: process
``i>0`` publishes to the derived ready file ``telemetry.p<i>.ready``
(:func:`simclr_tpu.obs.fleet.telemetry_ready_path`), which is how the
supervisor's ``FleetCollector`` discovers the whole fleet from one
configured path. Like the per-host heartbeat, the exporter renders only
host-side floats its own loop already fetched, so the zero-sync contract
holds on every host.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from simclr_tpu.utils.logging import get_logger

logger = get_logger()

DEFAULT_TRACE_MS = 1000.0
TRACE_DIR_NAME = "trace_on_demand"


class TelemetryHTTPServer(ThreadingHTTPServer):
    """Carries the telemetry registry and trace policy for its handlers."""

    daemon_threads = True

    def __init__(self, address, telemetry, save_dir: str, trace_max_ms: float):
        super().__init__(address, TelemetryHandler)
        self.telemetry = telemetry
        self.save_dir = save_dir
        self.trace_max_ms = float(trace_max_ms)
        self._trace_seq = 0
        self._trace_seq_lock = threading.Lock()

    def next_trace_dir(self) -> str:
        with self._trace_seq_lock:
            self._trace_seq += 1
            seq = self._trace_seq
        return os.path.join(
            self.save_dir, TRACE_DIR_NAME, f"trace-{int(time.time())}-{seq:03d}"
        )


class TelemetryHandler(BaseHTTPRequestHandler):
    server: TelemetryHTTPServer

    def log_message(self, fmt, *args):  # noqa: D102
        pass  # scrapes every few seconds would flood the training log

    def _send(self, code: int, body: bytes, content_type: str) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            # a scraper hanging up mid-response is routine, not an error:
            # count it instead of tracebacking onto the training log
            record = getattr(
                self.server.telemetry, "record_scrape_disconnect", None
            )
            if record is not None:
                record()
            self.close_connection = True

    def _send_json(self, code: int, payload: dict) -> None:
        self._send(code, json.dumps(payload).encode(), "application/json")

    def do_GET(self) -> None:  # noqa: N802
        path = urlsplit(self.path).path
        if path == "/metrics":
            self._send(
                200,
                self.server.telemetry.render().encode(),
                "text/plain; version=0.0.4",
            )
        elif path == "/healthz":
            self._send_json(
                200, {"status": "ok", **self.server.telemetry.snapshot()}
            )
        else:
            self._send_json(404, {"error": f"unknown path {path!r}"})

    def do_POST(self) -> None:  # noqa: N802
        url = urlsplit(self.path)
        if url.path != "/debug/trace":
            self._send_json(404, {"error": f"unknown path {url.path!r}"})
            return
        try:
            ms = float(parse_qs(url.query).get("ms", [DEFAULT_TRACE_MS])[0])
        except ValueError:
            self._send_json(400, {"error": "ms must be a number"})
            return
        if not 0 < ms <= self.server.trace_max_ms:
            self._send_json(
                400,
                {
                    "error": f"ms must be in (0, {self.server.trace_max_ms:g}] "
                    "(telemetry.trace_max_ms)"
                },
            )
            return
        # jax import deferred to first use: constructing the exporter must
        # stay cheap and device-free
        from simclr_tpu.utils.profiling import TraceInProgressError, capture_trace

        trace_dir = self.server.next_trace_dir()
        os.makedirs(trace_dir, exist_ok=True)
        try:
            capture_trace(trace_dir, ms / 1000.0)
        except TraceInProgressError as e:
            self._send_json(409, {"error": str(e)})
            return
        self._send_json(200, {"trace_dir": trace_dir, "ms": ms})


class TelemetryExporter:
    """The running exporter: server + daemon accept-loop thread."""

    def __init__(self, server: TelemetryHTTPServer, ready_file: str | None = None):
        self.server = server
        self.ready_file = ready_file
        self.host, self.port = server.server_address[:2]
        self._thread = threading.Thread(
            target=server.serve_forever,
            kwargs={"poll_interval": 0.2},
            name="telemetry-exporter",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self.server.shutdown()
        self._thread.join(timeout=5.0)
        self.server.server_close()
        if self.ready_file:
            # the published {host, port, pid} is dead the moment the socket
            # closes; leaving it behind would point orchestration at a port
            # some other process may reuse
            try:
                os.unlink(self.ready_file)
            except OSError:
                pass


def start_exporter(
    telemetry,
    save_dir: str,
    *,
    host: str = "127.0.0.1",
    port: int = 0,
    ready_file: str | None = None,
    trace_max_ms: float = 60000.0,
) -> TelemetryExporter:
    """Bind, publish the address if asked, and start serving (daemon)."""
    server = TelemetryHTTPServer((host, int(port)), telemetry, save_dir, trace_max_ms)
    exporter = TelemetryExporter(server, ready_file=str(ready_file) if ready_file else None)
    if ready_file:
        from simclr_tpu.utils.ioutil import atomic_write

        atomic_write(
            str(ready_file),
            lambda f: json.dump(
                {"host": exporter.host, "port": exporter.port, "pid": os.getpid()},
                f,
            ),
        )
    logger.info("telemetry exporter on http://%s:%d/metrics", exporter.host, exporter.port)
    return exporter


def maybe_start_exporter(
    cfg, telemetry, save_dir: str, *, process_index: int = 0
) -> TelemetryExporter | None:
    """The config-gated entry used by the trainers: ``telemetry.port=0``
    without a ready file (the default) means no exporter at all.

    Called on EVERY host with its ``jax.process_index()``: process ``i>0``
    publishes to the derived per-process ready file and, when a fixed port
    is configured, falls back to an ephemeral one — on single-machine
    multi-process dryruns every host would otherwise race for the same
    port. A bind failure on a non-zero process is logged and swallowed
    rather than killing a training host over a metrics socket.
    """
    port = int(cfg.select("telemetry.port", 0) or 0)
    ready_file = cfg.select("telemetry.ready_file")
    if port == 0 and not ready_file:
        return None
    if process_index:
        from simclr_tpu.obs.fleet import telemetry_ready_path

        if ready_file:
            ready_file = telemetry_ready_path(str(ready_file), process_index)
            port = 0
        else:
            # fixed port, no discovery file: plausible on real pods (one
            # process per machine), collision-prone on one machine
            try:
                return start_exporter(
                    telemetry,
                    save_dir,
                    host=str(cfg.select("telemetry.host", "127.0.0.1")),
                    port=port,
                    trace_max_ms=float(
                        cfg.select("telemetry.trace_max_ms", 60000)
                    ),
                )
            except OSError as e:
                logger.warning(
                    "telemetry exporter disabled on process %d: %s",
                    process_index, e,
                )
                return None
    return start_exporter(
        telemetry,
        save_dir,
        host=str(cfg.select("telemetry.host", "127.0.0.1")),
        port=port,
        ready_file=ready_file,
        trace_max_ms=float(cfg.select("telemetry.trace_max_ms", 60000)),
    )
