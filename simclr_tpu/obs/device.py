"""Live HBM accounting: per-device allocator gauges, preflight drift, OOM
forensics.

The epoch-compile preflight (``parallel/steps.py``) does analytic HBM math
once at startup; this module supplies the live ground truth. A
:class:`DeviceMonitor` attached to the telemetry registry samples
``device.memory_stats()`` for every local device **at render time** — i.e.
on the exporter's handler thread, per scrape. ``memory_stats`` is a
host-side allocator query (no device sync, no dispatch), so continuous
scraping keeps the zero-added-syncs contract of the whole telemetry stack
(counting-tested in tests/test_obs_device.py).

``memory_stats`` is backend-dependent: TPU/GPU report ``bytes_in_use`` /
``peak_bytes_in_use`` / ``bytes_limit``; CPU test meshes report nothing (or
raise). Every access is hardened — a backend without stats degrades to
absent per-device gauges, never a KeyError. The host-side high-watermark
gauge renders unconditionally (0 until a backend reports), so every
backend serves at least one ``simclr_train_hbm_*`` line (the
``scripts/obs_smoke.py`` contract).

On RESOURCE_EXHAUSTED the trainers call :func:`maybe_dump_oom_profile`:
a ``jax.profiler.device_memory_profile()`` forensic lands in the run dir
and an ``oom`` event in ``events.jsonl`` before the error re-raises — the
allocator's final state survives the crash.
"""

from __future__ import annotations

import os
import threading

OOM_EVENT = "oom"
HBM_EVENT = "hbm"

# the forensic pprof dump written next to events.jsonl on an allocator OOM
OOM_PROFILE_NAME = "oom_device_memory.prof"

# substrings identifying an allocator out-of-memory failure; XLA raises
# RESOURCE_EXHAUSTED, some backends phrase it as plain "out of memory"
OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")

# (memory_stats key, metric name, help) for the per-device gauges
HBM_GAUGES = (
    (
        "bytes_in_use",
        "simclr_train_hbm_bytes_in_use",
        "Live allocator bytes in use per local device",
    ),
    (
        "peak_bytes_in_use",
        "simclr_train_hbm_peak_bytes",
        "Allocator peak bytes in use per local device",
    ),
    (
        "bytes_limit",
        "simclr_train_hbm_bytes_limit",
        "Allocator capacity per local device",
    ),
)

# an hbm event is emitted when the high-watermark grows by this factor
# over the last emitted value (bounds the event count to O(log growth))
_EMIT_GROWTH_FACTOR = 1.1


def local_devices() -> list:
    """``jax.local_devices()``, or ``[]`` when jax/the backend is absent.

    Module-level so tests can monkeypatch in fake devices with synthetic
    ``memory_stats`` (CPU reports none).
    """
    try:
        import jax

        return list(jax.local_devices())
    except Exception:
        return []


def sample_memory_stats(device) -> dict | None:
    """Backend-hardened ``device.memory_stats()``: numeric keys or None.

    Filters to int/float values so a backend returning partial or exotic
    payloads can never leak a non-numeric value into a gauge; a backend
    without the API (or returning nothing) yields None.
    """
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    out = {}
    try:
        items = stats.items()
    except AttributeError:
        return None
    for key, value in items:
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            out[str(key)] = int(value)
    return out or None


class DeviceMonitor:
    """Per-device HBM sampler rendered into the ``/metrics`` payload.

    ``expected_resident_bytes`` is the analytic per-chip dataset footprint
    the epoch-compile preflight computed (``check_epoch_compile_
    preconditions``); when present, the drift gauge reports measured live
    bytes minus that analytic value — the preflight's reconciliation
    against ground truth. Thread-safe: scrapes arrive on exporter handler
    threads.
    """

    def __init__(
        self,
        *,
        events=None,
        expected_resident_bytes: int | None = None,
        devices=None,
    ):
        self.events = events
        self.expected_resident_bytes = (
            int(expected_resident_bytes)
            if expected_resident_bytes is not None
            else None
        )
        self._devices = devices
        self._lock = threading.Lock()
        self._peaks: dict[str, int] = {}
        self._high_watermark = 0
        self._last_emitted = 0

    # -- sampling (host-side allocator queries; zero device syncs) ---------
    def sample(self) -> dict[str, dict]:
        """One ``memory_stats`` pass over the local devices.

        Returns ``{device_label: {stat: bytes}}``; devices whose backend
        reports nothing are simply absent. Updates the per-device peaks
        and the run-wide high-watermark, and emits a rate-limited ``hbm``
        event when the watermark grows.
        """
        if self._devices is None:
            self._devices = local_devices()
        samples: dict[str, dict] = {}
        for i, device in enumerate(self._devices):
            stats = sample_memory_stats(device)
            if stats is None:
                continue
            label = str(getattr(device, "id", i))
            samples[label] = stats
            peak = max(
                stats.get("peak_bytes_in_use", 0), stats.get("bytes_in_use", 0)
            )
            with self._lock:
                if peak > self._peaks.get(label, 0):
                    self._peaks[label] = peak
                if peak > self._high_watermark:
                    self._high_watermark = peak
        self._maybe_emit(samples)
        return samples

    @property
    def high_watermark_bytes(self) -> int:
        with self._lock:
            return self._high_watermark

    def drift_bytes(self, samples: dict[str, dict]) -> int | None:
        """Measured live bytes minus the analytic preflight footprint.

        Uses the first sampled device's ``bytes_in_use`` (the preflight's
        budget math is per chip). None when either side is unknown.
        """
        if self.expected_resident_bytes is None or not samples:
            return None
        first = next(iter(samples.values()))
        in_use = first.get("bytes_in_use")
        if in_use is None:
            return None
        return int(in_use - self.expected_resident_bytes)

    def _maybe_emit(self, samples: dict[str, dict]) -> None:
        if self.events is None or not samples:
            return
        with self._lock:
            watermark = self._high_watermark
            if watermark <= self._last_emitted * _EMIT_GROWTH_FACTOR:
                return
            self._last_emitted = watermark
            peaks = dict(self._peaks)
        try:
            self.events.emit(
                HBM_EVENT,
                per_device=peaks,
                high_watermark=watermark,
                expected_resident_bytes=self.expected_resident_bytes,
                drift=self.drift_bytes(samples),
            )
        except Exception:
            pass

    # -- rendering (called from Telemetry.render on the exporter thread) ---
    def render(self) -> str:
        samples = self.sample()
        parts = []
        for key, name, help_text in HBM_GAUGES:
            lines = [
                f'{name}{{device="{label}"}} {stats[key]:g}'
                for label, stats in samples.items()
                if key in stats
            ]
            if lines:
                parts.append(
                    f"# HELP {name} {help_text}\n"
                    f"# TYPE {name} gauge\n" + "\n".join(lines) + "\n"
                )
        # unconditional: every backend serves at least one HBM gauge
        parts.append(
            "# HELP simclr_train_hbm_high_watermark_bytes Highest per-device "
            "allocator peak observed this run (0 until the backend reports)\n"
            "# TYPE simclr_train_hbm_high_watermark_bytes gauge\n"
            f"simclr_train_hbm_high_watermark_bytes {self.high_watermark_bytes:g}\n"
        )
        drift = self.drift_bytes(samples)
        if drift is not None:
            parts.append(
                "# HELP simclr_train_hbm_preflight_drift_bytes Measured live "
                "bytes minus the analytic epoch-compile preflight footprint\n"
                "# TYPE simclr_train_hbm_preflight_drift_bytes gauge\n"
                f"simclr_train_hbm_preflight_drift_bytes {drift:g}\n"
            )
        return "".join(parts)


# -- OOM forensics ----------------------------------------------------------
def is_oom_error(exc: BaseException) -> bool:
    """Does this exception look like an allocator RESOURCE_EXHAUSTED?"""
    text = f"{type(exc).__name__}: {exc}"
    return any(marker in text for marker in OOM_MARKERS)


def maybe_dump_oom_profile(
    save_dir, exc: BaseException, *, events=None, profile_fn=None
) -> str | None:
    """On an allocator OOM: dump the device memory profile + ``oom`` event.

    Called from the trainers' crash path with the in-flight exception; a
    non-OOM error is a no-op. The ``jax.profiler.device_memory_profile()``
    pprof payload lands at ``<save_dir>/oom_device_memory.prof`` (what each
    live buffer is and who allocated it — the question a post-mortem asks
    first). Never raises: forensics must not mask the original error,
    which the caller re-raises.
    """
    if not is_oom_error(exc):
        return None
    path = os.path.join(str(save_dir), OOM_PROFILE_NAME)
    try:
        if profile_fn is None:
            import jax

            profile_fn = jax.profiler.device_memory_profile
        payload = profile_fn()
        os.makedirs(str(save_dir), exist_ok=True)
        with open(path, "wb") as f:
            f.write(payload)
    except Exception:
        path = None
    try:
        if events is not None:
            events.emit(OOM_EVENT, error=str(exc)[:500], profile=path)
    except Exception:
        pass
    return path


def maybe_monitor(
    cfg, *, events=None, expected_resident_bytes=None
) -> DeviceMonitor | None:
    """Config-gated constructor used by the trainers (process 0 only)."""
    if not bool(cfg.select("telemetry.hbm", True)):
        return None
    return DeviceMonitor(
        events=events, expected_resident_bytes=expected_resident_bytes
    )
