"""Post-mortem run reports: ``python -m simclr_tpu.obs.report <run_dir>``.

Merges everything a finished (or dead) run left behind — the
``events.jsonl`` timeline, the final ``heartbeat.json`` with its
telemetry snapshot, ``supervisor_summary.json`` when the run was
supervised — into one per-attempt post-mortem, and judges the run's
throughput against a named ``BENCH_*.json`` baseline:

    python -m simclr_tpu.obs.report results/run --baseline BENCH_TPU_CAPTURE.json

The last output line is always machine-greppable::

    run_report verdict: OK|REGRESSION|NO_BASELINE|NO_DATA (...)

``OK``/``REGRESSION`` mean a measured-vs-baseline imgs/sec/chip ratio
was actually computed (``REGRESSION`` when it falls below
``--threshold``); ``NO_BASELINE``/``NO_DATA`` mean the comparison could
not happen.  The CLI exits 0 whenever a report was produced — the
verdict line, not the exit code, carries the judgement (the
``run_report`` stage in ``scripts/tpu_watch.sh`` greps for it).

Deliberately jax-free (stdlib + ``obs.events`` + ``supervisor.heartbeat``,
both stdlib-only) so it runs on any machine holding the run directory.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

from simclr_tpu.obs.events import events_path, read_events_counted
from simclr_tpu.supervisor.heartbeat import heartbeat_path, read_heartbeat

# a fleet whose slowest host runs >25% behind its fastest is flagged: SPMD
# collectives make every step as slow as the slowest participant, so this
# much skew is pure fleet-wide waste
SKEW_FLAG_RATIO = 1.25

VERDICT_OK = "OK"
VERDICT_REGRESSION = "REGRESSION"
VERDICT_NO_BASELINE = "NO_BASELINE"
VERDICT_NO_DATA = "NO_DATA"

DEFAULT_THRESHOLD = 0.8

SUMMARY_NAME = "supervisor_summary.json"

_COUNTED_EVENTS = {
    "epoch": "epochs",
    "checkpoint": "checkpoints",
    "slow_step": "slow_steps",
    "stall": "stalls",
    "auto_trace": "auto_traces",
    "nan_rollback": "nan_rollbacks",
    "preempt": "preempts",
    "compile": "compiles",
    "recompile_alarm": "recompile_alarms",
    "oom": "ooms",
    "host_lost": "hosts_lost",
    "remesh": "remeshes",
    "grow_back": "grow_backs",
    "swap": "swaps",
    "swap_rejected": "swap_rejections",
    "reallocate": "reallocations",
}

COSCHED_SUMMARY_NAME = "cosched_summary.json"


def _load_json(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else None


def load_baseline(path: str) -> float | None:
    """imgs/sec/chip out of a ``BENCH_*.json`` artifact, or None.

    Handles both shapes the bench tooling writes: the committed capture
    (``{"payload": {"metric": "pretrain_imgs_per_sec_per_chip",
    "value": ...}}``) and a raw probe attempt (``{"parsed": {...}}`` —
    whose ``parsed`` is null when the probe died).
    """
    payload = _load_json(path)
    if payload is None:
        return None
    node = payload.get("payload") or payload.get("parsed") or payload
    if not isinstance(node, dict):
        return None
    if node.get("metric") == "pretrain_imgs_per_sec_per_chip":
        value = node.get("value")
    else:
        value = node.get("imgs_per_sec_per_chip")
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    return value if value > 0 else None


def _fleet_hosts(run_dir: str) -> dict[str, dict]:
    """One row per per-host heartbeat file: the post-mortem's view of each
    host's last known step/epoch/step-time (``heartbeat.json`` is host 0,
    ``heartbeat.p<i>.json`` is host ``i``)."""
    hosts: dict[str, dict] = {}
    for path in sorted(glob.glob(os.path.join(run_dir, "heartbeat*.json"))):
        base = os.path.basename(path)
        if base == "heartbeat.json":
            index = 0
        else:
            match = re.fullmatch(r"heartbeat\.p(\d+)\.json", base)
            if not match:
                continue
            index = int(match.group(1))
        beat = read_heartbeat(path)
        if beat is None:
            continue
        telemetry = beat.get("telemetry")
        telemetry = telemetry if isinstance(telemetry, dict) else {}
        hosts[str(index)] = {
            "step": beat.get("step"),
            "epoch": beat.get("epoch"),
            "status": beat.get("status"),
            "beat_time": beat.get("time"),
            "step_time_s": telemetry.get("step_time_s"),
            "imgs_per_sec": telemetry.get("imgs_per_sec"),
        }
    return hosts


def build_report(
    run_dir: str,
    *,
    baseline_path: str | None = None,
    threshold: float = DEFAULT_THRESHOLD,
) -> dict:
    # torn lines (a crash mid-O_APPEND truncates at most the tail line) are
    # skipped but COUNTED: the report must say the timeline is incomplete
    # instead of silently under-reporting or tracebacking on json.loads
    events, torn_lines = read_events_counted(events_path(run_dir))
    attempts: dict[int, dict] = {}
    for event in events:
        try:
            attempt = int(event.get("attempt", 1))
        except (TypeError, ValueError):
            attempt = 1
        entry = attempts.setdefault(
            attempt,
            {
                **{field: 0 for field in _COUNTED_EVENTS.values()},
                "exit": None,
                "hung": False,
                "first_time": None,
                "last_time": None,
                "compile_seconds": 0.0,
                "hbm_peak_per_device": {},
                "host_transitions": [],
            },
        )
        kind = event.get("event")
        if kind in _COUNTED_EVENTS:
            entry[_COUNTED_EVENTS[kind]] += 1
        if kind == "remesh":
            # per-attempt host timeline, rendered as "hosts: 2→1→2"
            transitions = entry["host_transitions"]
            before, after = event.get("hosts_before"), event.get("hosts_after")
            if isinstance(before, int) and not transitions:
                transitions.append(before)
            if isinstance(after, int):
                transitions.append(after)
        elif kind == "child_exit":
            entry["exit"] = event.get("exit")
            entry["hung"] = bool(event.get("hung"))
        if kind == "compile":
            seconds = event.get("seconds")
            if isinstance(seconds, (int, float)):
                entry["compile_seconds"] = round(
                    entry["compile_seconds"] + seconds, 6
                )
        elif kind == "hbm":
            per_device = event.get("per_device")
            if isinstance(per_device, dict):
                peaks = entry["hbm_peak_per_device"]
                for device, peak in per_device.items():
                    if isinstance(peak, (int, float)):
                        peaks[str(device)] = max(
                            peaks.get(str(device), 0), int(peak)
                        )
        when = event.get("time")
        if isinstance(when, (int, float)):
            if entry["first_time"] is None:
                entry["first_time"] = when
            entry["last_time"] = when
    for entry in attempts.values():
        if entry["first_time"] is not None:
            entry["duration_s"] = round(entry["last_time"] - entry["first_time"], 3)
        else:
            entry["duration_s"] = None

    stalled = sorted(
        a for a, entry in attempts.items() if entry["stalls"] or entry["hung"]
    )

    # run-level host timeline ("hosts: 2→1→2") stitched from the remesh
    # events in order; empty for non-elastic runs
    hosts_timeline: list[int] = []
    for event in events:
        if event.get("event") != "remesh":
            continue
        before, after = event.get("hosts_before"), event.get("hosts_after")
        if isinstance(before, int) and not hosts_timeline:
            hosts_timeline.append(before)
        if isinstance(after, int):
            hosts_timeline.append(after)

    heartbeat = read_heartbeat(heartbeat_path(run_dir))
    telemetry = None
    if heartbeat is not None and isinstance(heartbeat.get("telemetry"), dict):
        telemetry = heartbeat["telemetry"]
    supervisor = _load_json(os.path.join(run_dir, SUMMARY_NAME))

    # co-scheduled serve plane: checkpoint hot-swaps, rejected swaps, and
    # train/serve device reallocations interleave with the training events
    # in the same run dir — the combined train+serve post-mortem
    cosched = _load_json(os.path.join(run_dir, COSCHED_SUMMARY_NAME))
    swap_events = [e for e in events if e.get("event") == "swap"]
    reject_events = [e for e in events if e.get("event") == "swap_rejected"]
    realloc_events = [e for e in events if e.get("event") == "reallocate"]
    serve = None
    if swap_events or reject_events or realloc_events or cosched:
        serve = {
            "swaps": len(swap_events),
            "swap_rejections": len(reject_events),
            "reallocations": sum(
                1 for e in realloc_events if e.get("direction") == "shrink"
            ),
            "releases": sum(
                1 for e in realloc_events if e.get("direction") == "release"
            ),
            "serving_generation": (
                swap_events[-1].get("generation")
                if swap_events
                else (cosched or {}).get("serving_generation", 0)
            ),
            "last_swap_epoch": (
                swap_events[-1].get("epoch") if swap_events else None
            ),
            "serve_replicas": (cosched or {}).get("serve_replicas"),
            "corpus_generation": (cosched or {}).get("corpus_generation"),
            "corpus_rows": (cosched or {}).get("corpus_rows"),
        }

    # fleet view: one row per heartbeat.p<i>.json (every host beats), the
    # skew/slowest verdict from the supervisor's embedded FleetCollector
    # snapshot when present, recomputed from the beats otherwise
    hosts = _fleet_hosts(run_dir)
    fleet = (
        supervisor.get("fleet")
        if supervisor and isinstance(supervisor.get("fleet"), dict)
        else None
    )
    skew, slowest = None, None
    if fleet is not None:
        skew = fleet.get("step_time_skew_ratio") or None
        slowest = fleet.get("slowest_host")
    if skew is None:
        step_times = {
            h: row["step_time_s"]
            for h, row in hosts.items()
            if isinstance(row.get("step_time_s"), (int, float))
            and row["step_time_s"] > 0
        }
        if step_times:
            slowest = max(step_times, key=step_times.get)
            skew = round(step_times[slowest] / min(step_times.values()), 4)

    measured = None
    if telemetry is not None:
        try:
            measured = float(telemetry.get("imgs_per_sec_per_chip"))
        except (TypeError, ValueError):
            measured = None
        if measured is not None and measured <= 0:
            measured = None

    baseline = load_baseline(baseline_path) if baseline_path else None

    ratio = None
    if not events and heartbeat is None:
        verdict = VERDICT_NO_DATA
    elif baseline is None:
        verdict = VERDICT_NO_BASELINE
    elif measured is None:
        verdict = VERDICT_NO_DATA
    else:
        ratio = measured / baseline
        verdict = VERDICT_OK if ratio >= threshold else VERDICT_REGRESSION

    return {
        "run_dir": os.path.abspath(run_dir),
        "attempts": {str(a): attempts[a] for a in sorted(attempts)},
        "stalled_attempts": stalled,
        "hosts_timeline": hosts_timeline,
        "torn_lines": torn_lines,
        "hosts": hosts,
        "fleet": fleet,
        "step_time_skew_ratio": skew,
        "slowest_host": slowest,
        "outcome": supervisor.get("outcome") if supervisor else None,
        "supervisor": supervisor,
        "serve": serve,
        "cosched": cosched,
        "heartbeat": heartbeat,
        "telemetry": telemetry,
        "measured_imgs_per_sec_per_chip": measured,
        "baseline_imgs_per_sec_per_chip": baseline,
        "threshold": threshold,
        "throughput_ratio": round(ratio, 4) if ratio is not None else None,
        "verdict": verdict,
    }


def render_report(report: dict) -> str:
    lines = [f"run report: {report['run_dir']}"]
    if report["outcome"] is not None:
        supervisor = report["supervisor"] or {}
        lines.append(
            f"outcome: {report['outcome']} "
            f"(exit {supervisor.get('exit')}, "
            f"resumed {supervisor.get('resumed', 0)}x)"
        )
    if report.get("hosts_timeline"):
        lines.append(
            "hosts: " + "→".join(str(n) for n in report["hosts_timeline"])
        )
    if report.get("torn_lines"):
        lines.append(
            f"WARNING: {report['torn_lines']} torn event line(s) skipped "
            "(events.jsonl truncated mid-write)"
        )
    for attempt, entry in report["attempts"].items():
        duration = (
            f"{entry['duration_s']:.1f}s"
            if entry["duration_s"] is not None
            else "?"
        )
        exit_part = "" if entry["exit"] is None else f" exit={entry['exit']}"
        hung_part = " HUNG" if entry["hung"] else ""
        lines.append(
            f"attempt {attempt}: {duration} epochs={entry['epochs']} "
            f"checkpoints={entry['checkpoints']} "
            f"slow_steps={entry['slow_steps']} stalls={entry['stalls']} "
            f"auto_traces={entry['auto_traces']} "
            f"nan_rollbacks={entry['nan_rollbacks']} "
            f"preempts={entry['preempts']}{exit_part}{hung_part}"
        )
        if entry["compiles"] or entry["recompile_alarms"] or entry["ooms"]:
            alarm_part = (
                f" RECOMPILE_ALARMS={entry['recompile_alarms']}"
                if entry["recompile_alarms"] else ""
            )
            oom_part = f" OOMS={entry['ooms']}" if entry["ooms"] else ""
            lines.append(
                f"  compiles: {entry['compiles']} "
                f"({entry['compile_seconds']:.2f}s total)"
                f"{alarm_part}{oom_part}"
            )
        if entry.get("hosts_lost") or entry.get("grow_backs"):
            transition = (
                " hosts: "
                + "→".join(str(n) for n in entry["host_transitions"])
                if entry.get("host_transitions") else ""
            )
            lines.append(
                f"  elastic: hosts_lost={entry['hosts_lost']} "
                f"remeshes={entry['remeshes']} "
                f"grow_backs={entry['grow_backs']}{transition}"
            )
        if entry["hbm_peak_per_device"]:
            peaks = " ".join(
                f"dev{device}={peak / 2 ** 30:.2f}GiB"
                for device, peak in sorted(entry["hbm_peak_per_device"].items())
            )
            lines.append(f"  hbm peak: {peaks}")
    if report["stalled_attempts"]:
        lines.append(
            "stalled attempts: "
            + ", ".join(str(a) for a in report["stalled_attempts"])
        )
    hosts = report.get("hosts") or {}
    if len(hosts) > 1 or report.get("fleet") is not None:
        skew = report.get("step_time_skew_ratio")
        if skew is not None:
            verdict = "STRAGGLER" if skew > SKEW_FLAG_RATIO else "even"
            skew_part = (
                f" skew={skew:.2f}x ({verdict},"
                f" slowest=host {report.get('slowest_host')})"
            )
        else:
            skew_part = ""
        fleet = report.get("fleet") or {}
        up_part = (
            f" up={fleet['hosts_up']}/{fleet['hosts_expected']}"
            if "hosts_up" in fleet else ""
        )
        lines.append(f"fleet: hosts={len(hosts)}{up_part}{skew_part}")
        for host, row in sorted(hosts.items(), key=lambda kv: int(kv[0])):
            step_time = (
                f"{row['step_time_s']:.4f}s"
                if isinstance(row.get("step_time_s"), (int, float))
                else "?"
            )
            rate = (
                f"{row['imgs_per_sec']:.1f}"
                if isinstance(row.get("imgs_per_sec"), (int, float))
                else "?"
            )
            lines.append(
                f"  host {host}: step={row.get('step')} "
                f"epoch={row.get('epoch')} step_time={step_time} "
                f"imgs/s={rate}"
            )
        trace = os.path.join(report["run_dir"], "timeline_trace.json")
        if os.path.exists(trace):
            lines.append(f"timeline: {trace}")
        else:
            lines.append(
                "timeline: python -m simclr_tpu.obs.timeline "
                f"{report['run_dir']}"
            )
    serve = report.get("serve")
    if serve:
        reject_part = (
            f" REJECTED={serve['swap_rejections']}"
            if serve.get("swap_rejections") else ""
        )
        replica_part = (
            f" replicas={serve['serve_replicas']}"
            if serve.get("serve_replicas") is not None else ""
        )
        corpus_part = (
            f" corpus=gen{serve['corpus_generation']}/"
            f"{serve.get('corpus_rows')}rows"
            if serve.get("corpus_generation") is not None else ""
        )
        lines.append(
            f"serve: swaps={serve['swaps']}{reject_part} "
            f"generation={serve.get('serving_generation')} "
            f"reallocations={serve['reallocations']} "
            f"(released {serve['releases']}){replica_part}{corpus_part}"
        )
        if serve.get("last_swap_epoch") is not None:
            lines.append(f"  last swap: epoch {serve['last_swap_epoch']}")
    telemetry = report.get("telemetry") or {}
    if telemetry.get("exposed_comm_ms") is not None:
        # step time beyond roofline compute — the wire the scheduler did NOT
        # hide; compare runs across comm_overlap=off|chunked|async
        lines.append(
            f"exposed comm: {float(telemetry['exposed_comm_ms']):.3f} ms/step"
        )
    detail = (
        f"imgs/s/chip measured={report['measured_imgs_per_sec_per_chip']} "
        f"baseline={report['baseline_imgs_per_sec_per_chip']} "
        f"ratio={report['throughput_ratio']} "
        f"threshold={report['threshold']}"
    )
    # keep this the LAST line and the format stable: tooling greps
    # '^run_report verdict: ' (scripts/tpu_watch.sh run_report stage)
    lines.append(f"run_report verdict: {report['verdict']} ({detail})")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m simclr_tpu.obs.report",
        description="Per-attempt post-mortem of a run directory with a "
        "throughput-regression verdict.",
    )
    parser.add_argument("run_dir", help="run save_dir holding events.jsonl etc.")
    parser.add_argument(
        "--baseline",
        default=None,
        help="BENCH_*.json artifact holding the imgs/sec/chip baseline",
    )
    parser.add_argument(
        "--threshold",
        type=float,
        default=DEFAULT_THRESHOLD,
        help="minimum measured/baseline ratio judged OK (default %(default)s)",
    )
    parser.add_argument(
        "--json", default=None, help="also write the full report to this path"
    )
    args = parser.parse_args(argv)

    report = build_report(
        args.run_dir, baseline_path=args.baseline, threshold=args.threshold
    )
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
    print(render_report(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
