"""Run observability: metrics primitives, telemetry registry, event timeline.

One subsystem shared by every tier of the system (docs/OBSERVABILITY.md):

  * :mod:`~simclr_tpu.obs.metrics` — dependency-free Counter/Gauge/Summary/
    Histogram rendered in the Prometheus text exposition format (promoted
    out of ``serve/metrics.py``, which re-exports them unchanged);
  * :mod:`~simclr_tpu.obs.telemetry` — the training-side metric registry
    (step time, imgs/s, MFU, loss/lr, allreduce wire bytes, checkpoint
    durations), fed only host-side floats the loop already fetched;
  * :mod:`~simclr_tpu.obs.events` — structured ``events.jsonl`` timeline in
    the run dir, shared by the trainers and the supervisor runner;
  * :mod:`~simclr_tpu.obs.exporter` — process-0 daemon HTTP exporter
    (``/metrics``, ``/healthz``, ``POST /debug/trace?ms=N``);
  * :mod:`~simclr_tpu.obs.trace` — request-scoped span tracing for the
    serve tier (``X-Request-Id``, ``GET /debug/slow``, ``requests.jsonl``);
  * :mod:`~simclr_tpu.obs.anomaly` — rolling median/MAD step anomaly
    detector with a stall watchdog and rate-limited automatic profiler
    captures;
  * :mod:`~simclr_tpu.obs.report` — post-mortem run reports with a
    throughput-regression verdict (``python -m simclr_tpu.obs.report``).

``metrics``, ``events``, ``trace``, and ``report`` are stdlib-only so the
supervisor runner and the serve tier import them without paying for (or
touching) jax; ``telemetry``, ``anomaly``, and ``exporter`` defer anything
heavier to call time.
"""

from __future__ import annotations

from simclr_tpu.obs.events import EventLog, events_path, read_events
from simclr_tpu.obs.metrics import Counter, Gauge, Histogram, Summary

__all__ = [
    "Counter",
    "EventLog",
    "Gauge",
    "Histogram",
    "Summary",
    "Telemetry",
    "events_path",
    "read_events",
]


def __getattr__(name):
    # Telemetry imports parallel/compress (jax) — load lazily so stdlib-only
    # consumers (supervisor runner, serve) keep their import footprint
    if name == "Telemetry":
        from simclr_tpu.obs.telemetry import Telemetry

        return Telemetry
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
