"""Compile-side observability: cost extraction, compile records, recompile
sentry.

The host-side telemetry (PR 6/7) sees the run through step wall times; this
module watches the *compiler* boundary — the other place a TPU run silently
loses its performance:

* **Cost attribution** — :func:`executable_cost` extracts XLA's analytic
  flops / bytes-accessed from an AOT-compiled executable (promoted from
  ``scripts/perf_attrib.py`` so the one-off attribution script and the live
  telemetry share one extraction), and the per-executable numbers are
  exported as labeled gauges plus a roofline-vs-XLA MFU drift signal.
* **Compile records** — every lower+compile is timed, fingerprinted
  (sha256 of the lowered StableHLO/jaxpr text), and emitted as a
  ``compile`` event into ``events.jsonl`` — wall time, fingerprint,
  analytic cost in one line.
* **Recompile sentry** — a step function that recompiles after warmup is
  the classic silent TPU perf killer (a shape or dtype drifted and every
  step now pays a multi-second compile). :meth:`CompileSentry.watch` wraps
  a jitted step in an explicit AOT lower/compile cache keyed by the
  abstract argument signature, so any post-warmup compilation is observed
  the moment it happens: alarm counter + ``recompile_alarm`` event +
  the PR 7 rate-limited auto-trace hook.

``watch`` prefers the AOT path (``fn.lower(*args).compile()`` — the only
way to both time a compile precisely and keep the executable for
fingerprint/cost analysis) and degrades to plain dispatch with
call-duration timing when a backend or wrapper lacks ``lower``. Stdlib-only
at import time; jax is imported lazily inside the signature helper.
"""

from __future__ import annotations

import hashlib
import threading
import time

COMPILE_EVENT = "compile"
RECOMPILE_ALARM_EVENT = "recompile_alarm"


def executable_cost(compiled) -> tuple[float, float]:
    """(flops, bytes_accessed) from an AOT executable's XLA cost analysis.

    The extraction ``scripts/perf_attrib.py`` used privately, promoted so
    live telemetry and the attribution script agree by construction.
    ``cost_analysis`` may return a per-computation list (older jax) or one
    dict; missing keys and backends without cost analysis degrade to
    ``(0.0, 0.0)`` rather than raising.
    """
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        return float(ca.get("flops", 0.0)), float(ca.get("bytes accessed", 0.0))
    except Exception:
        return 0.0, 0.0


def lowered_fingerprint(lowered) -> str:
    """Stable hex fingerprint of a lowered program's StableHLO/jaxpr text.

    Two lowerings of the same function at the same abstract signature hash
    identically, so a changed fingerprint in a ``compile`` event names a
    genuinely different program, not a re-run.
    """
    try:
        text = lowered.as_text()
    except Exception:
        return ""
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def args_signature(args: tuple) -> tuple:
    """Hashable abstract signature of a call's arguments.

    Array leaves contribute ``(shape, dtype)``; non-array leaves (python
    scalars — jit's weak types) contribute their type only, so a step
    counter changing value does not look like a new program. This is the
    compile-cache key the sentry's AOT cache shares with jit's own
    dispatch logic for our purposes: same signature, same executable.
    """
    import jax

    def leaf_sig(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            return (type(x).__name__,)
        return (tuple(shape), str(dtype))

    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (tuple(leaf_sig(leaf) for leaf in leaves), str(treedef))


class CompileSentry:
    """Registry of observed XLA compilations for one run.

    ``record_compile`` is the single funnel: it counts, emits the
    ``compile`` event, pushes cost numbers into telemetry, and — when the
    compile happened after warmup (``warm=True``) — raises the recompile
    alarm (counter + ``recompile_alarm`` event + auto-trace hook).
    ``auto_trace`` is the detector's ``_maybe_auto_trace(reason, seconds)``
    bound method (PR 7), so alarms share its cooldown and budget.
    """

    def __init__(
        self,
        *,
        telemetry=None,
        events=None,
        auto_trace=None,
        clock=time.perf_counter,
    ):
        self.telemetry = telemetry
        self.events = events
        self._auto_trace = auto_trace
        self._clock = clock
        self._lock = threading.Lock()
        self.records: list[dict] = []
        self.compiles = 0
        self.recompile_alarms = 0

    def record_compile(
        self,
        name: str,
        *,
        seconds: float,
        fingerprint: str = "",
        flops: float = 0.0,
        bytes_accessed: float = 0.0,
        steps_per_call: int = 1,
        warm: bool = False,
    ) -> dict:
        """Book one observed compilation of executable ``name``.

        ``steps_per_call`` normalizes cost for epoch-compiled programs (one
        executable runs a whole epoch's scan) so per-step cost gauges and
        the MFU drift compare like with like. ``warm=True`` marks a
        post-warmup compilation and fires the alarm path.
        """
        steps = max(int(steps_per_call), 1)
        record = {
            "name": str(name),
            "seconds": float(seconds),
            "fingerprint": fingerprint,
            "flops": float(flops),
            "bytes_accessed": float(bytes_accessed),
            "steps_per_call": steps,
            "recompile": bool(warm),
        }
        with self._lock:
            self.records.append(record)
            self.compiles += 1
            if warm:
                self.recompile_alarms += 1
        if self.telemetry is not None:
            self.telemetry.record_compile(seconds)
            self.telemetry.observe_xla_cost(
                name,
                flops_per_step=record["flops"] / steps,
                bytes_per_step=record["bytes_accessed"] / steps,
            )
        if self.events is not None:
            self.events.emit(
                COMPILE_EVENT,
                name=record["name"],
                seconds=round(record["seconds"], 6),
                fingerprint=fingerprint,
                flops=record["flops"],
                bytes_accessed=record["bytes_accessed"],
                recompile=bool(warm),
            )
        if warm:
            if self.telemetry is not None:
                self.telemetry.record_recompile_alarm()
            if self.events is not None:
                self.events.emit(
                    RECOMPILE_ALARM_EVENT,
                    name=record["name"],
                    seconds=round(record["seconds"], 6),
                    fingerprint=fingerprint,
                )
            if self._auto_trace is not None:
                try:
                    self._auto_trace(RECOMPILE_ALARM_EVENT, record["seconds"])
                except Exception:
                    pass
        return record

    def watch(self, fn, name: str, *, steps_from_args=None):
        """Wrap a jitted callable so its every compilation is observed."""
        return WatchedFunction(fn, name, self, steps_from_args=steps_from_args)


class WatchedFunction:
    """AOT lower/compile wrapper around one jitted step function.

    Keeps its own signature-keyed executable cache — each new abstract
    signature triggers an explicit ``lower`` + timed ``compile`` whose
    executable is fingerprinted and cost-analyzed, then cached; repeat
    signatures dispatch straight to the cached executable. Donation and
    sharding are captured at lowering, so the compiled program behaves
    exactly like the jit dispatch it replaces. A signature seen after the
    first completed call means the step function recompiled after warmup —
    the sentry's alarm condition.

    Called from the single training-loop thread (matching how the step
    functions it wraps are used); the sentry's own bookkeeping is locked.
    """

    def __init__(self, fn, name: str, sentry: CompileSentry, *, steps_from_args=None):
        self._fn = fn
        self.name = str(name)
        self._sentry = sentry
        self._steps_from_args = steps_from_args
        self._cache: dict = {}
        self._calls = 0

    def _steps_per_call(self, args) -> int:
        if self._steps_from_args is None:
            return 1
        try:
            return max(int(self._steps_from_args(args)), 1)
        except Exception:
            return 1

    def __call__(self, *args):
        sig = args_signature(args)
        entry = self._cache.get(sig)
        if entry is not None:
            self._calls += 1
            return entry(*args)
        warm = self._calls > 0
        clock = self._sentry._clock
        t0 = clock()
        compiled = None
        fingerprint = ""
        flops = bytes_accessed = 0.0
        try:
            lowered = self._fn.lower(*args)
            fingerprint = lowered_fingerprint(lowered)
            compiled = lowered.compile()
            flops, bytes_accessed = executable_cost(compiled)
        except Exception:
            compiled = None
        if compiled is None:
            # no AOT on this backend/wrapper: dispatch plainly — the first
            # call at a new signature still IS the compiling call, so its
            # duration is the (upper-bound) compile time
            out = self._fn(*args)
            self._sentry.record_compile(
                self.name,
                seconds=clock() - t0,
                warm=warm,
                steps_per_call=self._steps_per_call(args),
            )
            self._cache[sig] = self._fn
            self._calls += 1
            return out
        self._sentry.record_compile(
            self.name,
            seconds=clock() - t0,
            fingerprint=fingerprint,
            flops=flops,
            bytes_accessed=bytes_accessed,
            warm=warm,
            steps_per_call=self._steps_per_call(args),
        )
        self._cache[sig] = compiled
        self._calls += 1
        return compiled(*args)


def maybe_sentry(cfg, *, telemetry=None, events=None, detector=None):
    """Config-gated constructor used by the trainers (process 0 only).

    Reuses the anomaly detector's rate-limited auto-trace (cooldown +
    per-attempt budget) as the alarm's capture hook when one is running.
    """
    if not bool(cfg.select("telemetry.compile_sentry", True)):
        return None
    auto_trace = detector._maybe_auto_trace if detector is not None else None
    return CompileSentry(
        telemetry=telemetry, events=events, auto_trace=auto_trace
    )
