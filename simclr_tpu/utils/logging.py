"""Run logging, reference-style.

The reference configures stdlib logging per entry point with a plain
StreamHandler and logs one line per epoch from rank 0 only
(``/root/reference/main.py:136-141,124-127``). Under SPMD there is one
process per host; process 0 is the logging host (the rank-0 analogue).
"""

from __future__ import annotations

import logging
import os
import sys

import jax


def get_logger(name: str = "simclr_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        # under the supervisor runner each restart tags its lines with the
        # attempt ordinal, so an interleaved log reads unambiguously
        attempt = os.environ.get("SIMCLR_SUPERVISOR_ATTEMPT", "").strip()
        tag = f" [attempt {attempt}]" if attempt else ""
        handler.setFormatter(
            logging.Formatter(f"%(asctime)s %(levelname)s{tag} %(message)s")
        )
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def is_logging_host() -> bool:
    """True on the process that logs/saves (the reference's rank-0 gate,
    ``/root/reference/main.py:124``)."""
    return jax.process_index() == 0
