"""Learning-rate scaling and warmup+cosine schedule, reference-exact.

Reproduces the reference's step accounting bit-for-bit (SURVEY §2.5.11-12),
because LR-curve drift is one of the named hard parts for quality parity:

  * base LR scaling by the PER-DEVICE batch: ``lr * B / 256`` (linear) or
    ``lr * sqrt(B)`` (``/root/reference/lr_utils.py:5-15`` — note the
    reference scales by the per-GPU batch, not the global batch).
  * per-step linear warmup with the ``<=`` boundary: step ``warmup_steps``
    itself still takes the warmup value (``/root/reference/main.py:106``).
  * cosine annealing with ``T_max = total_steps - warmup_steps`` whose index
    advances only *after* each post-warmup step, so step ``warmup + 1 + t``
    uses cosine index ``t`` (``/root/reference/main.py:96-99,119-120``).
  * ``steps_per_epoch = N // (B * n_data_shards)`` — the reference's
    ``drop_last=True`` truncation (``/root/reference/main.py:76-77``).
"""

from __future__ import annotations

import math

import jax.numpy as jnp


def calculate_initial_lr(base_lr: float, batch_size: int, linear_schedule: bool) -> float:
    """Scaled base LR (``/root/reference/lr_utils.py:5-15``)."""
    if linear_schedule:
        return base_lr * batch_size / 256.0
    return base_lr * math.sqrt(batch_size)


def steps_per_epoch(num_samples: int, per_device_batch: int, n_data_shards: int) -> int:
    """Reference drop-last truncation (``/root/reference/main.py:76-77``)."""
    return int(num_samples / (per_device_batch * n_data_shards))


def warmup_cosine_schedule(
    initial_lr: float, total_steps: int, warmup_steps: int
):
    """Returns ``schedule(step) -> lr`` (jnp-traceable, optax-compatible).

    step <= warmup_steps : linear warmup ``step / warmup_steps * lr0``
                           (lr0 exactly at the boundary; lr0 at step 0 when
                           warmup_steps == 0).
    step >  warmup_steps : ``0.5 * lr0 * (1 + cos(pi * t / T_max))`` with
                           ``t = step - warmup_steps - 1`` and
                           ``T_max = total_steps - warmup_steps`` — the torch
                           CosineAnnealingLR trajectory as driven by the
                           reference loop.
    """
    t_max = max(total_steps - warmup_steps, 1)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warmup_lr = jnp.where(
            warmup_steps > 0,
            step / jnp.maximum(warmup_steps, 1) * initial_lr,
            initial_lr,
        )
        # clamp at t_max so evaluation past total_steps (resume overrun,
        # step miscount) floors at the cosine minimum instead of wrapping up
        t = jnp.clip(step - warmup_steps - 1.0, 0.0, float(t_max))
        cosine_lr = 0.5 * initial_lr * (1.0 + jnp.cos(jnp.pi * t / t_max))
        return jnp.where(step <= warmup_steps, warmup_lr, cosine_lr)

    return schedule
