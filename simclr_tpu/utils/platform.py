"""Restore standard ``JAX_PLATFORMS`` semantics for CLI entry points.

Some environments pin a platform via ``jax.config.update('jax_platforms',
...)`` in ``sitecustomize`` at interpreter startup, which silently overrides
the ``JAX_PLATFORMS`` environment variable users rely on (e.g.
``JAX_PLATFORMS=cpu python -m simclr_tpu.main ...`` for a CPU-mesh smoke
run). Calling :func:`ensure_platform` before first device use re-applies the
environment variable with config precedence. No-op when the variable is
unset or devices are already initialized.
"""

from __future__ import annotations

import os

import jax


def ensure_platform() -> None:
    env = os.environ.get("JAX_PLATFORMS", "").strip()
    if env:
        jax.config.update("jax_platforms", env)
