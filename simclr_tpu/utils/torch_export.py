"""Export Flax variables as reference-compatible PyTorch state dicts.

The inverse of :mod:`simclr_tpu.utils.torch_import`: checkpoints trained in
this framework become ``.pt`` state dicts the reference's own tooling
consumes directly (``torch.load`` + ``load_state_dict`` in
``/root/reference/eval.py:256-263`` / ``save_features.py:146-149``), so a
reference user can migrate in either direction — pretrain here, probe
there, or vice versa.

Key mapping is the import shim's, inverted (see torch_import's table);
conv kernels go HWIO->OIHW, linear kernels transpose back to (out, in).
``num_batches_tracked`` — present in every torch BN state dict but never
read by the reference's load path — is emitted as 0 so ``strict=True``
loads succeed.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from simclr_tpu.models.arch import (
    BLOCK_NAME as _BLOCK_NAME,
    CONVS_PER_BLOCK as _CONVS_PER_BLOCK,
    STAGE_SIZES as _STAGE_SIZES,
)


def _np(x) -> np.ndarray:
    return np.asarray(x)


def _conv_out(w) -> np.ndarray:
    """flax HWIO -> torch OIHW."""
    return _np(w).transpose(3, 2, 0, 1)


def _linear_out(w) -> np.ndarray:
    """flax (in, out) -> torch (out, in)."""
    return _np(w).T


def _export_bn(sd: dict, torch_key: str, p_node: Mapping, s_node: Mapping) -> None:
    sd[f"{torch_key}.weight"] = _np(p_node["scale"])
    sd[f"{torch_key}.bias"] = _np(p_node["bias"])
    sd[f"{torch_key}.running_mean"] = _np(s_node["mean"])
    sd[f"{torch_key}.running_var"] = _np(s_node["var"])
    sd[f"{torch_key}.num_batches_tracked"] = np.asarray(0, dtype=np.int64)


def _export_encoder(
    sd: dict, params: Mapping, stats: Mapping, base_cnn: str, torch_prefix: str = "f."
) -> None:
    block_name = _BLOCK_NAME[base_cnn]
    n_convs = _CONVS_PER_BLOCK[base_cnn]
    f_p, f_s = params["f"], stats["f"]

    sd[f"{torch_prefix}conv1.weight"] = _conv_out(f_p["stem_conv"]["kernel"])
    _export_bn(sd, f"{torch_prefix}bn1", f_p["BatchNorm_0"], f_s["BatchNorm_0"])

    block_idx = 0
    for stage, num_blocks in enumerate(_STAGE_SIZES[base_cnn], start=1):
        for b in range(num_blocks):
            tp = f"{torch_prefix}layer{stage}.{b}."
            bp, bs = f_p[f"{block_name}_{block_idx}"], f_s[f"{block_name}_{block_idx}"]
            for c in range(n_convs):
                sd[f"{tp}conv{c + 1}.weight"] = _conv_out(bp[f"Conv_{c}"]["kernel"])
                _export_bn(sd, f"{tp}bn{c + 1}", bp[f"BatchNorm_{c}"], bs[f"BatchNorm_{c}"])
            if f"Conv_{n_convs}" in bp:  # projection shortcut (torch downsample)
                sd[f"{tp}downsample.0.weight"] = _conv_out(bp[f"Conv_{n_convs}"]["kernel"])
                _export_bn(
                    sd, f"{tp}downsample.1", bp[f"BatchNorm_{n_convs}"], bs[f"BatchNorm_{n_convs}"]
                )
            block_idx += 1


def export_contrastive_state_dict(
    variables: Mapping[str, Any], base_cnn: str = "resnet18", ddp_prefix: bool = False
) -> dict[str, np.ndarray]:
    """``{params, batch_stats}`` -> reference ``ContrastiveModel`` state dict.

    ``ddp_prefix=True`` prepends ``module.`` to every key, mimicking the
    reference's DDP-wrapped saves (its eval strips the prefix anyway).
    """
    params, stats = variables["params"], variables["batch_stats"]
    sd: dict[str, np.ndarray] = {}
    _export_encoder(sd, params, stats, base_cnn)
    g_p, g_s = params["g"], stats["g"]
    sd["g.projection_head.0.weight"] = _linear_out(g_p["linear1"]["kernel"])
    sd["g.projection_head.0.bias"] = _np(g_p["linear1"]["bias"])
    _export_bn(sd, "g.projection_head.1", g_p["bn1"], g_s["bn1"])
    sd["g.projection_head.3.weight"] = _linear_out(g_p["linear2"]["kernel"])
    if ddp_prefix:
        sd = {f"module.{k}": v for k, v in sd.items()}
    return sd


def export_supervised_state_dict(
    variables: Mapping[str, Any], base_cnn: str = "resnet18", ddp_prefix: bool = False
) -> dict[str, np.ndarray]:
    """``{params, batch_stats}`` -> reference ``SupervisedModel`` state dict."""
    params, stats = variables["params"], variables["batch_stats"]
    sd: dict[str, np.ndarray] = {}
    _export_encoder(sd, params, stats, base_cnn)
    sd["fc.weight"] = _linear_out(params["fc"]["kernel"])
    sd["fc.bias"] = _np(params["fc"]["bias"])
    if ddp_prefix:
        sd = {f"module.{k}": v for k, v in sd.items()}
    return sd


def save_torch_checkpoint(
    path: str,
    variables: Mapping[str, Any],
    base_cnn: str = "resnet18",
    kind: str = "contrastive",
    ddp_prefix: bool = False,
) -> None:
    """Write a ``.pt`` the reference's ``torch.load`` consumes (needs torch)."""
    import torch

    if kind == "contrastive":
        sd = export_contrastive_state_dict(variables, base_cnn, ddp_prefix)
    elif kind == "supervised":
        sd = export_supervised_state_dict(variables, base_cnn, ddp_prefix)
    else:
        raise ValueError(f"kind must be contrastive|supervised, got {kind!r}")
    # copy=True: exported arrays can be read-only jax buffers, and torch
    # refuses (warns on) non-writable storage
    torch.save({k: torch.from_numpy(np.array(v, copy=True)) for k, v in sd.items()}, path)
