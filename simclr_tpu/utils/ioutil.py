"""Crash-safe file writes shared by the entry points.

Every artifact a resume gate later trusts (eval's ``results.json``,
save_features' ``.npy`` exports) must hit the filesystem atomically: a
SIGKILL mid-write must leave either the old file or the new one, never a
truncated hybrid that an existence check would carry forward as complete.

``bench.py``'s ``persist_tpu_capture`` deliberately keeps its own copy of
this pattern: the bench orchestrator imports no package code at all
(importing ``simclr_tpu`` pulls jax via ``utils.platform``, and the
orchestrator must stay jax-free so a hung TPU tunnel cannot hang it).
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable, IO

# probed once at import: os.umask(0)+restore is a process-global race — a
# thread opening files between the two calls would briefly create
# world-writable artifacts (eval and save_features both write from worker
# threads)
_UMASK = os.umask(0)
os.umask(_UMASK)


def atomic_append(path: str, text: str) -> None:
    """Append ``text`` to ``path`` as ONE ``write(2)`` on an ``O_APPEND`` fd.

    POSIX makes the seek-to-end and the write atomic together, so concurrent
    appenders (the training child and the supervisor runner both write
    ``events.jsonl``) interleave whole records, never torn ones — provided
    each record is a single write, which is why this takes the full string
    rather than a file object. No fsync: timeline events are forensics, not
    resume gates (same trade as ``supervisor/heartbeat.py``).
    """
    data = text.encode()
    fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o666 & ~_UMASK)
    try:
        os.write(fd, data)
    finally:
        os.close(fd)


def atomic_write(path: str, write_fn: Callable[[IO], None], mode: str = "w") -> None:
    """Write via ``write_fn(file)`` to a unique temp file, then rename.

    ``mode`` is ``"w"`` for text (json.dump) or ``"wb"`` for binary
    (np.save). The rename is atomic on POSIX; the tmp file lives in the
    destination directory so the replace never crosses filesystems. The
    tmp name is unique per call (ADVICE r4: a fixed ``path + ".tmp"``
    lets two concurrent writers corrupt the winner — writer A's open fd
    keeps writing into the inode writer B renamed into place), and the
    data is fsynced before the rename so a power loss cannot surface an
    empty file under the final name.
    """
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".", prefix=os.path.basename(path) + ".tmp."
    )
    try:
        # mkstemp creates 0600; restore umask-governed permissions so shared
        # artifacts (results JSON, feature exports) stay readable as before
        os.fchmod(fd, 0o666 & ~_UMASK)
        with os.fdopen(fd, mode) as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
