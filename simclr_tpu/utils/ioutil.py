"""Crash-safe file writes shared by the entry points.

Every artifact a resume gate later trusts (eval's ``results.json``,
save_features' ``.npy`` exports) must hit the filesystem atomically: a
SIGKILL mid-write must leave either the old file or the new one, never a
truncated hybrid that an existence check would carry forward as complete.

``bench.py``'s ``persist_tpu_capture`` deliberately keeps its own copy of
this pattern: the bench orchestrator imports no package code at all
(importing ``simclr_tpu`` pulls jax via ``utils.platform``, and the
orchestrator must stay jax-free so a hung TPU tunnel cannot hang it).
"""

from __future__ import annotations

import os
from typing import Callable, IO


def atomic_write(path: str, write_fn: Callable[[IO], None], mode: str = "w") -> None:
    """Write via ``write_fn(file)`` to ``path + ".tmp"``, then rename.

    ``mode`` is ``"w"`` for text (json.dump) or ``"wb"`` for binary
    (np.save). The rename is atomic on POSIX; the tmp file lives in the
    destination directory so the replace never crosses filesystems.
    """
    tmp = path + ".tmp"
    with open(tmp, mode) as f:
        write_fn(f)
    os.replace(tmp, path)
