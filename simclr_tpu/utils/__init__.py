from simclr_tpu.utils.schedule import (
    calculate_initial_lr,
    steps_per_epoch,
    warmup_cosine_schedule,
)

__all__ = ["calculate_initial_lr", "steps_per_epoch", "warmup_cosine_schedule"]
