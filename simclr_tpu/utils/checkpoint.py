"""Checkpoint save/restore (orbax) — params + optimizer + step.

The reference is save-only and params-only: rank 0 ``torch.save``s the
DDP-wrapped ``state_dict`` every ``save_model_epoch`` epochs
(``/root/reference/main.py:129-131``) and nothing can resume mid-run (SURVEY
§5.3-4). Here the whole :class:`TrainState` pytree (params, BN stats,
optimizer state, step counter) round-trips through orbax, giving exact
resume; loading just the model variables for eval/export is the restricted
case of the same mechanism.

Checkpoint directories are ``<save_dir>/epoch=<E>-<name>`` mirroring the
reference's ``epoch={E}-{output_model_name}`` filename scheme
(``main.py:129-131``) minus the ``.pt`` suffix, so downstream globbing in
eval/save_features enumerates them the same way the reference globs ``*.pt``
(``eval.py:248``).
"""

from __future__ import annotations

import hashlib
import os
import re

import jax
import numpy as np
import orbax.checkpoint as ocp

_EPOCH_RE = re.compile(r"epoch=(\d+)-")

# integrity sidecar written next to each checkpoint directory: sha256 over
# every file the checkpoint contains, so a consumer (the serving engine
# above all — it must never answer traffic from a truncated restore) can
# verify the bytes on disk are the bytes that were saved
DIGEST_SUFFIX = ".sha256"


def digest_path(path: str) -> str:
    """Sidecar path for a checkpoint directory (``<path>.sha256``)."""
    return path.rstrip("/") + DIGEST_SUFFIX


def checkpoint_digest(path: str) -> str:
    """sha256 hex digest over a checkpoint directory's full contents.

    Hashes every regular file in sorted relative-path order, framing each
    with its path and size so file renames, truncations, and content swaps
    all change the digest. Deterministic across hosts: orbax writes the
    same bytes it later reads, and the walk order is sorted, not
    filesystem-dependent.
    """
    h = hashlib.sha256()
    path = os.path.abspath(path)
    files = []
    for root, _dirs, names in os.walk(path):
        for name in names:
            full = os.path.join(root, name)
            files.append((os.path.relpath(full, path), full))
    for rel, full in sorted(files):
        size = os.path.getsize(full)
        h.update(f"{rel}\x00{size}\x00".encode())
        with open(full, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
    return h.hexdigest()


def checkpoint_name(epoch: int, output_model_name: str) -> str:
    """``epoch=<E>-<stem>`` (reference: ``f"epoch={epoch}-{name}.pt"``)."""
    stem = output_model_name
    if stem.endswith(".pt"):
        stem = stem[: -len(".pt")]
    return f"epoch={epoch}-{stem}"


def epoch_of(path: str) -> int:
    """Parse the epoch out of a checkpoint directory name (-1 if absent)."""
    m = _EPOCH_RE.search(os.path.basename(path.rstrip("/")))
    return int(m.group(1)) if m else -1


def list_checkpoints(target_dir: str) -> list[str]:
    """All checkpoint dirs under ``target_dir``, epoch-sorted.

    The eval/export analogue of the reference's ``Path(...).glob("*.pt")``
    (``/root/reference/eval.py:248``).
    """
    if not os.path.isdir(target_dir):
        return []
    out = []
    for entry in os.listdir(target_dir):
        full = os.path.join(target_dir, entry)
        # skip orbax's in-progress tmp dirs (name carries the final dir's
        # "epoch=" prefix): a crash mid-save must not offer a half-written
        # checkpoint to resume/eval. Integrity sidecars and atomic_write
        # temp files also carry the "epoch=" prefix and must never be
        # enumerated as checkpoints (they are files, but be explicit).
        if "orbax-checkpoint-tmp" in entry:
            continue
        if entry.endswith(DIGEST_SUFFIX) or ".tmp." in entry:
            continue
        if os.path.isdir(full) and _EPOCH_RE.search(entry):
            out.append(full)
    # Within an epoch, a "-preempt" checkpoint sorts AFTER the plain boundary
    # checkpoint: it was taken mid-way through the NEXT epoch, so it holds
    # strictly more steps. (A name tiebreak alone would get this wrong for
    # stems that sort before "preempt", e.g. "epoch=2-model" < "epoch=2-model-preempt"
    # but "epoch=2-preempt-…" < "epoch=2-supervised-…".)
    return sorted(
        out,
        key=lambda p: (
            epoch_of(p),
            1 if "-preempt" in os.path.basename(p) else 0,
            os.path.basename(p),
        ),
    )


def list_checkpoints_or_raise(target_dir: str) -> list[str]:
    """:func:`list_checkpoints`, raising ``FileNotFoundError`` when empty —
    the shared preflight of every checkpoint-consuming entry point
    (eval / save_features / export_torch)."""
    checkpoints = list_checkpoints(target_dir)
    if not checkpoints:
        raise FileNotFoundError(f"no checkpoints found under {target_dir!r}")
    return checkpoints


def save_checkpoint(path: str, state) -> None:
    """Save a pytree (TrainState or plain dict) to ``path`` atomically.

    After the orbax save commits, process 0 writes a sha256 sidecar
    (``<path>.sha256``, via ``ioutil.atomic_write`` so a crash leaves either
    no sidecar or a complete one) that :func:`restore_checkpoint` verifies
    before trusting the bytes — a truncated or bit-rotted checkpoint fails
    loudly at load instead of silently serving garbage embeddings.
    """
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)
    if jax.process_index() == 0:
        from simclr_tpu.utils.ioutil import atomic_write

        digest = checkpoint_digest(path)
        atomic_write(
            digest_path(path),
            lambda f: f.write(f"{digest}  {os.path.basename(path)}\n"),
        )


class CheckpointCorruptionError(ValueError):
    """The on-disk checkpoint bytes do not match their recorded digest."""


def verify_checkpoint(path: str) -> bool:
    """Check ``path`` against its sha256 sidecar.

    Returns True when the digest matches, False when no sidecar exists (a
    legacy checkpoint saved before integrity sidecars landed — callers
    warn, not fail), and raises :class:`CheckpointCorruptionError` on a
    mismatch or an unparseable sidecar.
    """
    sidecar = digest_path(os.path.abspath(path))
    if not os.path.exists(sidecar):
        return False
    with open(sidecar) as f:
        recorded = f.read().split()
    if not recorded or len(recorded[0]) != 64:
        raise CheckpointCorruptionError(
            f"unparseable checkpoint digest sidecar {sidecar!r}"
        )
    actual = checkpoint_digest(path)
    if actual != recorded[0]:
        raise CheckpointCorruptionError(
            f"checkpoint {path!r} does not match its recorded sha256 "
            f"(recorded {recorded[0][:12]}…, actual {actual[:12]}…): the "
            f"checkpoint is truncated or corrupt — do not resume or serve "
            f"from it"
        )
    return True


def restore_checkpoint(path: str, target=None, *, verify: bool = True):
    """Restore into the structure/shardings of ``target``; with ``target=None``
    return the raw pytree (dict of numpy arrays) — the eval/export load path.

    With ``verify=True`` (default) the sha256 sidecar is checked first when
    present; legacy checkpoints without a sidecar load with a warning.
    """
    path = os.path.abspath(path)
    if verify:
        if not verify_checkpoint(path):
            from simclr_tpu.utils.logging import get_logger

            get_logger().warning(
                "checkpoint %s has no sha256 sidecar (saved before integrity "
                "sidecars landed); loading unverified", path,
            )
    if target is None:
        # Host-numpy restore, independent of the saving topology: the
        # StandardCheckpointer default re-applies the SAVED shardings, so a
        # checkpoint written on an 8-device mesh refuses to load in a
        # single-device process (train on a pod, serve/eval on one chip).
        with ocp.PyTreeCheckpointer() as ckptr:
            meta = ckptr.metadata(path)
            tree = getattr(meta, "tree", None) or meta
            restore_args = jax.tree.map(
                lambda _: ocp.RestoreArgs(restore_type=np.ndarray), tree
            )
            return ckptr.restore(path, restore_args=restore_args)
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(path, target)


def delete_checkpoint(path: str) -> None:
    """Remove a checkpoint directory and its digest sidecar (the supervised
    best-only policy, ``/root/reference/supervised.py:151-162``)."""
    import shutil

    if os.path.isdir(path):
        shutil.rmtree(path)
    sidecar = digest_path(path)
    if os.path.exists(sidecar):
        os.unlink(sidecar)


def latest_checkpoint(save_dir: str) -> str | None:
    """Newest checkpoint in a run dir, for ``--resume`` semantics."""
    ckpts = list_checkpoints(save_dir)
    return ckpts[-1] if ckpts else None


def restore_checkpoint_with_fallback(save_dir: str, target=None):
    """Restore the newest checkpoint whose sha256 sidecar verifies.

    Walks the run's checkpoints newest-first; a corrupt one (digest mismatch)
    is logged and skipped, and restore falls back to the next-older verified
    checkpoint — losing a few epochs of progress beats losing the run.
    Returns ``(restored, path)``, or ``(None, None)`` when the directory holds
    no checkpoints at all (a fresh run). Raises
    :class:`CheckpointCorruptionError` only when checkpoints exist but NONE
    verifies — there is nothing trustworthy to resume from.
    """
    from simclr_tpu.utils.logging import get_logger

    ckpts = list_checkpoints(save_dir)
    if not ckpts:
        return None, None
    skipped = []
    for path in reversed(ckpts):
        try:
            restored = restore_checkpoint(path, target)
        except CheckpointCorruptionError as e:
            skipped.append(path)
            get_logger().warning(
                "skipping corrupt checkpoint %s (%s); falling back to the "
                "previous one", path, e,
            )
            continue
        if skipped:
            get_logger().warning(
                "restored %s after skipping %d corrupt checkpoint(s): %s",
                path, len(skipped), ", ".join(os.path.basename(p) for p in skipped),
            )
        return restored, path
    raise CheckpointCorruptionError(
        f"all {len(ckpts)} checkpoint(s) under {save_dir!r} fail sha256 "
        f"verification; nothing trustworthy to resume from"
    )
