"""Checkpoint save/restore (orbax) — params + optimizer + step.

The reference is save-only and params-only: rank 0 ``torch.save``s the
DDP-wrapped ``state_dict`` every ``save_model_epoch`` epochs
(``/root/reference/main.py:129-131``) and nothing can resume mid-run (SURVEY
§5.3-4). Here the whole :class:`TrainState` pytree (params, BN stats,
optimizer state, step counter) round-trips through orbax, giving exact
resume; loading just the model variables for eval/export is the restricted
case of the same mechanism.

Checkpoint directories are ``<save_dir>/epoch=<E>-<name>`` mirroring the
reference's ``epoch={E}-{output_model_name}`` filename scheme
(``main.py:129-131``) minus the ``.pt`` suffix, so downstream globbing in
eval/save_features enumerates them the same way the reference globs ``*.pt``
(``eval.py:248``).
"""

from __future__ import annotations

import os
import re

import jax
import orbax.checkpoint as ocp

_EPOCH_RE = re.compile(r"epoch=(\d+)-")


def checkpoint_name(epoch: int, output_model_name: str) -> str:
    """``epoch=<E>-<stem>`` (reference: ``f"epoch={epoch}-{name}.pt"``)."""
    stem = output_model_name
    if stem.endswith(".pt"):
        stem = stem[: -len(".pt")]
    return f"epoch={epoch}-{stem}"


def epoch_of(path: str) -> int:
    """Parse the epoch out of a checkpoint directory name (-1 if absent)."""
    m = _EPOCH_RE.search(os.path.basename(path.rstrip("/")))
    return int(m.group(1)) if m else -1


def list_checkpoints(target_dir: str) -> list[str]:
    """All checkpoint dirs under ``target_dir``, epoch-sorted.

    The eval/export analogue of the reference's ``Path(...).glob("*.pt")``
    (``/root/reference/eval.py:248``).
    """
    if not os.path.isdir(target_dir):
        return []
    out = []
    for entry in os.listdir(target_dir):
        full = os.path.join(target_dir, entry)
        # skip orbax's in-progress tmp dirs (name carries the final dir's
        # "epoch=" prefix): a crash mid-save must not offer a half-written
        # checkpoint to resume/eval
        if "orbax-checkpoint-tmp" in entry:
            continue
        if os.path.isdir(full) and _EPOCH_RE.search(entry):
            out.append(full)
    return sorted(out, key=epoch_of)


def list_checkpoints_or_raise(target_dir: str) -> list[str]:
    """:func:`list_checkpoints`, raising ``FileNotFoundError`` when empty —
    the shared preflight of every checkpoint-consuming entry point
    (eval / save_features / export_torch)."""
    checkpoints = list_checkpoints(target_dir)
    if not checkpoints:
        raise FileNotFoundError(f"no checkpoints found under {target_dir!r}")
    return checkpoints


def save_checkpoint(path: str, state) -> None:
    """Save a pytree (TrainState or plain dict) to ``path`` atomically."""
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)


def restore_checkpoint(path: str, target=None):
    """Restore into the structure/shardings of ``target``; with ``target=None``
    return the raw pytree (dict of numpy arrays) — the eval/export load path."""
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        if target is None:
            return ckptr.restore(path)
        return ckptr.restore(path, target)


def delete_checkpoint(path: str) -> None:
    """Remove a checkpoint directory (the supervised best-only policy,
    ``/root/reference/supervised.py:151-162``)."""
    import shutil

    if os.path.isdir(path):
        shutil.rmtree(path)


def latest_checkpoint(save_dir: str) -> str | None:
    """Newest checkpoint in a run dir, for ``--resume`` semantics."""
    ckpts = list_checkpoints(save_dir)
    return ckpts[-1] if ckpts else None
