"""Device array -> host numpy, multi-host safe — the one fetch helper.

Shared by eval's feature extraction, save_features' augmentation averaging,
and the serving engine (``simclr_tpu/serve/engine.py``) so every surface
that materializes device output on the host goes through the same
multi-host-aware path (previously a private ``eval._fetch`` that
save_features reached into across modules).
"""

from __future__ import annotations

import jax
import numpy as np


def fetch(x: jax.Array) -> np.ndarray:
    """Device array -> host numpy, multi-host safe.

    Under multi-host SPMD a sharded output spans chips this process cannot
    address; ``process_allgather`` assembles the full array on every host
    (the arrays fetched here are small: N x 512 floats). Single-process,
    this is a plain ``np.asarray`` value fetch — which doubles as a true
    completion fence (see ``utils.profiling.synchronize``).
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)
