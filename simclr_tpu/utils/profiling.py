"""Profiling & step-timing — a subsystem the reference lacks entirely.

SURVEY §5.1: the reference has no profiler hooks or timers anywhere. TPU
builds live or die by the profile, so this module provides:

  * :func:`start_server` — ``jax.profiler`` trace server for live capture
    (connect with TensorBoard / xprof);
  * :func:`trace` — context manager writing a trace for a code region;
  * :class:`StepTimer` — value-fetch-bracketed step timing with imgs/sec and
    imgs/sec/chip (the BASELINE.json north-star metric).
"""

from __future__ import annotations

import contextlib
import threading
import time

import jax
import numpy as np


def synchronize(tree) -> None:
    """Wait until ``tree``'s computation has actually finished on device.

    ``jax.block_until_ready`` is NOT a reliable fence on remote-tunneled
    runtimes: it can return while steps are still queued, which inflates
    short-window throughput measurements by >10x (observed on the axon TPU
    tunnel). Fetching a VALUE cannot lie — the scalar only exists once the
    producing computation (and, through data dependence, everything it
    consumed) has run. Fetches one element of EVERY array leaf — leaves may
    come from independent dispatches, so fencing only the first would leave
    the others queued.
    """
    if tree is None:
        return
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array):
            # fetch from this process's first shard: works for sharded
            # arrays that are not fully addressable (multi-process), and a
            # one-element slice avoids dispatching a full-array reshape/copy
            # just to prove completion
            shard = leaf.addressable_shards[0].data
            if shard.size:
                shard = shard[(0,) * shard.ndim]
            np.asarray(jax.device_get(shard))


def start_server(port: int = 9999):
    """Start the profiler server; returns the server object (keep it alive)."""
    return jax.profiler.start_server(port)


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a profiler trace of the enclosed region into ``log_dir``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class TraceInProgressError(RuntimeError):
    """A profiler capture is already running (jax allows one at a time)."""


# serializes on-demand captures (obs/exporter.py handler threads); a capture
# racing a StepTraceWindow still fails inside jax, reported as this error
_capture_lock = threading.Lock()


def capture_trace(log_dir: str, seconds: float) -> None:
    """Blocking on-demand profiler capture of the NEXT ``seconds`` of device
    activity into ``log_dir`` (the ``POST /debug/trace?ms=N`` backend).

    The capture rides alongside the training loop without touching it: the
    profiler observes whatever the devices are doing, so this adds no sync
    to the loop — only the exporter's handler thread sleeps.
    """
    if not _capture_lock.acquire(blocking=False):
        raise TraceInProgressError("a profiler capture is already in progress")
    try:
        try:
            jax.profiler.start_trace(str(log_dir))
        except Exception as e:  # e.g. a StepTraceWindow already tracing
            raise TraceInProgressError(str(e)) from e
        try:
            time.sleep(max(float(seconds), 0.0))
        finally:
            jax.profiler.stop_trace()
    finally:
        _capture_lock.release()


class StepTraceWindow:
    """Capture a profiler trace of steps [start, start+length) of a loop.

    Call :meth:`tick` once per step with the host step index (before
    running the step); call :meth:`close` after the loop — the trace is
    stopped there too if the window ran past the end of training.
    """

    def __init__(self, log_dir: str | None, start: int, length: int, enabled: bool = True):
        self.log_dir = log_dir
        self.start = start
        self.stop_at = start + length
        self.enabled = bool(log_dir) and enabled
        self._active = False

    def tick(self, step: int, pending=None) -> None:
        if not self.enabled:
            return
        if not self._active and step == self.start:
            jax.profiler.start_trace(str(self.log_dir))
            self._active = True
        elif self._active and step >= self.stop_at:
            synchronize(pending)
            jax.profiler.stop_trace()
            self._active = False
            self.enabled = False

    def close(self, pending=None) -> None:
        if self._active:
            synchronize(pending)
            jax.profiler.stop_trace()
            self._active = False
            self.enabled = False


def time_step_loop(step, state, batches, rng, warmup: int, steps: int):
    """Time ``steps`` invocations of a compiled ``(state, batch, rng) ->
    (state, metrics)`` train step with value-fetch synchronization.

    The shared measurement methodology for bench.py and
    scripts/perf_explore.py: warmup (draining the dispatch queue with a
    device->host VALUE fetch each iteration — ``block_until_ready`` can
    return before remote-tunneled dispatch queues drain, inflating
    short-window rates by >10x), then a timed window closed by a final
    value fetch. Returns ``(seconds, final_loss, state)``.
    """
    import jax

    metrics = None
    for i in range(warmup):
        state, metrics = step(state, batches[i % len(batches)], jax.random.fold_in(rng, i))
        float(metrics["loss"])
    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = step(
            state, batches[i % len(batches)], jax.random.fold_in(rng, 100 + i)
        )
    final_loss = float(metrics["loss"])  # value fetch = true synchronization
    dt = time.perf_counter() - t0
    return dt, final_loss, state


class StepTimer:
    """Steady-state throughput measurement for a compiled step.

    Usage::

        timer = StepTimer(global_batch, warmup=3)
        for i in range(n):
            out = step(...)
            timer.tick(out)        # blocks on the first post-warmup tick only
        print(timer.summary())
    """

    def __init__(self, global_batch: int, warmup: int = 3):
        if warmup < 1:
            # timing starts at the warmup-th tick; with warmup=0 no tick
            # would ever set t0 and summary() would silently report zeros
            raise ValueError("warmup must be >= 1 (the first step compiles)")
        self.global_batch = global_batch
        self.warmup = warmup
        self._count = 0
        self._t0: float | None = None
        self._timed_steps = 0
        self._last = None
        self._paused_at: float | None = None
        self._excluded = 0.0

    def tick(self, device_output=None) -> None:
        self._count += 1
        self._last = device_output
        if self._count == self.warmup:
            synchronize(device_output)
            self._t0 = time.perf_counter()
        elif self._count > self.warmup:
            self._timed_steps += 1

    def pause(self, device_output=None) -> None:
        """Exclude a non-step interval (checkpoint save, eval sweep) from the
        timed window. Fences outstanding step work first, so the excluded
        span contains only the paused activity."""
        if self._t0 is not None and self._paused_at is None:
            synchronize(device_output if device_output is not None else self._last)
            self._paused_at = time.perf_counter()

    def resume(self) -> None:
        if self._paused_at is not None:
            self._excluded += time.perf_counter() - self._paused_at
            self._paused_at = None

    def summary(self) -> dict:
        if self._t0 is None or self._timed_steps == 0:
            return {"imgs_per_sec": 0.0, "imgs_per_sec_per_chip": 0.0, "steps": 0}
        self.resume()
        synchronize(self._last)
        dt = time.perf_counter() - self._t0 - self._excluded
        imgs_per_sec = self._timed_steps * self.global_batch / dt
        return {
            "imgs_per_sec": imgs_per_sec,
            "imgs_per_sec_per_chip": imgs_per_sec / jax.device_count(),
            "steps": self._timed_steps,
            "seconds": dt,
        }
