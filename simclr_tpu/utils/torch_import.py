"""Import reference PyTorch checkpoints into Flax variables (parity shim).

Lets users of the reference bring their trained ``*.pt`` state dicts
(saved by ``/root/reference/main.py:129-131`` — DDP-wrapped, so keys carry a
``module.`` prefix) straight into this framework for eval/export, and lets
the test suite check numerical parity model-against-model.

Key mapping (torchvision resnet18/34/50 + reference heads -> our Flax tree):

  torchvision                      flax (this repo)
  ------------------------------   -----------------------------------------
  f.conv1.weight                   f/stem_conv/kernel          (OIHW->HWIO)
  f.bn1.{weight,bias}              f/BatchNorm_0/{scale,bias}
  f.bn1.running_{mean,var}         batch_stats f/BatchNorm_0/{mean,var}
  f.layerL.B.convN.weight          f/Block_{i}/Conv_{N-1}/kernel
  f.layerL.B.bnN.*                 f/Block_{i}/BatchNorm_{N-1}/*
  f.layerL.B.downsample.0/1        f/Block_{i}/Conv_{last}/BatchNorm_{last}
  g.projection_head.0.{weight,b}   g/linear1/{kernel,bias}     (OI->IO)
  g.projection_head.1.*            g/bn1/*
  g.projection_head.3.weight       g/linear2/kernel
  fc.{weight,bias}                 fc/{kernel,bias}            (SupervisedModel)

where Block is BasicBlock (resnet18/34) or BottleneckBlock (resnet50/101)
and ``i``
counts blocks across stages in order. torch tensors are converted via
numpy; torch itself is an optional dependency (only needed to unpickle
``.pt`` files — dict inputs work without it).
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from simclr_tpu.models.arch import (  # single source of truth for the zoo
    BLOCK_NAME as _BLOCK_NAME,
    CONVS_PER_BLOCK as _CONVS_PER_BLOCK,
    STAGE_SIZES as _STAGE_SIZES,
)


def _to_numpy(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    return t.detach().cpu().numpy()  # torch tensor


def _conv(w) -> np.ndarray:
    """torch OIHW -> flax HWIO."""
    return _to_numpy(w).transpose(2, 3, 1, 0)


def _linear(w) -> np.ndarray:
    """torch (out, in) -> flax (in, out)."""
    return _to_numpy(w).T


def strip_ddp_prefix(state_dict: Mapping[str, Any]) -> dict[str, Any]:
    """Remove the DDP ``module.`` prefix (``/root/reference/eval.py:257``)."""
    return {k.removeprefix("module."): v for k, v in state_dict.items()}


def _set(tree: dict, path: tuple[str, ...], value: np.ndarray) -> None:
    node = tree
    for part in path[:-1]:
        node = node.setdefault(part, {})
    node[path[-1]] = value


def _import_bn(
    params: dict, stats: dict, prefix: tuple[str, ...], sd: Mapping, torch_key: str
) -> None:
    _set(params, prefix + ("scale",), _to_numpy(sd[f"{torch_key}.weight"]))
    _set(params, prefix + ("bias",), _to_numpy(sd[f"{torch_key}.bias"]))
    _set(stats, prefix + ("mean",), _to_numpy(sd[f"{torch_key}.running_mean"]))
    _set(stats, prefix + ("var",), _to_numpy(sd[f"{torch_key}.running_var"]))


def _import_encoder(
    params: dict, stats: dict, sd: Mapping, base_cnn: str, torch_prefix: str = "f."
) -> None:
    block_name = _BLOCK_NAME[base_cnn]
    n_convs = _CONVS_PER_BLOCK[base_cnn]

    _set(params, ("f", "stem_conv", "kernel"), _conv(sd[f"{torch_prefix}conv1.weight"]))
    _import_bn(params, stats, ("f", "BatchNorm_0"), sd, f"{torch_prefix}bn1")

    block_idx = 0
    for stage, num_blocks in enumerate(_STAGE_SIZES[base_cnn], start=1):
        for b in range(num_blocks):
            tp = f"{torch_prefix}layer{stage}.{b}."
            fp = ("f", f"{block_name}_{block_idx}")
            for c in range(n_convs):
                _set(
                    params, fp + (f"Conv_{c}", "kernel"),
                    _conv(sd[f"{tp}conv{c + 1}.weight"]),
                )
                _import_bn(params, stats, fp + (f"BatchNorm_{c}",), sd, f"{tp}bn{c + 1}")
            if f"{tp}downsample.0.weight" in sd:
                _set(
                    params, fp + (f"Conv_{n_convs}", "kernel"),
                    _conv(sd[f"{tp}downsample.0.weight"]),
                )
                _import_bn(
                    params, stats, fp + (f"BatchNorm_{n_convs}",), sd, f"{tp}downsample.1"
                )
            block_idx += 1


def import_contrastive_state_dict(
    state_dict: Mapping[str, Any], base_cnn: str = "resnet18"
) -> dict[str, Any]:
    """Reference ``ContrastiveModel`` state dict -> ``{params, batch_stats}``.

    Covers encoder ``f`` plus projection head ``g`` (Linear->BN1d->ReLU->
    Linear-no-bias, ``/root/reference/model.py:65-70``).
    """
    sd = strip_ddp_prefix(state_dict)
    params: dict[str, Any] = {}
    stats: dict[str, Any] = {}
    _import_encoder(params, stats, sd, base_cnn)

    _set(params, ("g", "linear1", "kernel"), _linear(sd["g.projection_head.0.weight"]))
    _set(params, ("g", "linear1", "bias"), _to_numpy(sd["g.projection_head.0.bias"]))
    _import_bn(params, stats, ("g", "bn1"), sd, "g.projection_head.1")
    _set(params, ("g", "linear2", "kernel"), _linear(sd["g.projection_head.3.weight"]))
    return {"params": params, "batch_stats": stats}


def import_supervised_state_dict(
    state_dict: Mapping[str, Any], base_cnn: str = "resnet18"
) -> dict[str, Any]:
    """Reference ``SupervisedModel`` state dict (encoder + ``fc`` head)."""
    sd = strip_ddp_prefix(state_dict)
    params: dict[str, Any] = {}
    stats: dict[str, Any] = {}
    _import_encoder(params, stats, sd, base_cnn)
    _set(params, ("fc", "kernel"), _linear(sd["fc.weight"]))
    _set(params, ("fc", "bias"), _to_numpy(sd["fc.bias"]))
    return {"params": params, "batch_stats": stats}


def load_torch_checkpoint(
    path: str, base_cnn: str = "resnet18", kind: str = "contrastive"
) -> dict[str, Any]:
    """Load a reference ``.pt`` file from disk (requires torch to unpickle)."""
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    if kind == "contrastive":
        return import_contrastive_state_dict(sd, base_cnn)
    if kind == "supervised":
        return import_supervised_state_dict(sd, base_cnn)
    raise ValueError(f"kind must be contrastive|supervised, got {kind!r}")
