"""CLI: export orbax checkpoints as reference-compatible ``.pt`` files.

Closes the migration loop from the command line (the library surface is
:mod:`simclr_tpu.utils.torch_export`): every checkpoint directory under
``--target-dir`` (the same enumeration eval/save_features use, mirroring
the reference's ``*.pt`` glob over ``experiment.target_dir``) becomes a
``<name>.pt`` state dict the reference's own ``torch.load`` +
``load_state_dict`` consume (``/root/reference/eval.py:256-263``).

    python -m simclr_tpu.export_torch \
        --target-dir results/cifar10/seed-7/... --out-dir exported/

Plain argparse rather than the Hydra-style config tree: this tool is an
auxiliary bridge with no reference counterpart, so it takes no recipe
keys — only paths and the model identity.
"""

from __future__ import annotations

import argparse
import os

from simclr_tpu.utils.checkpoint import list_checkpoints_or_raise
from simclr_tpu.utils.torch_export import save_torch_checkpoint


def main(argv: list[str] | None = None) -> list[str]:
    from simclr_tpu.utils.platform import ensure_platform

    ensure_platform()
    ap = argparse.ArgumentParser(
        prog="python -m simclr_tpu.export_torch", description=__doc__
    )
    ap.add_argument("--target-dir", required=True,
                    help="directory of orbax checkpoint dirs (epoch=N-...)")
    ap.add_argument("--out-dir", required=True)
    ap.add_argument("--base-cnn", default="resnet18")
    ap.add_argument("--kind", choices=["contrastive", "supervised"],
                    default="contrastive")
    ap.add_argument("--ddp-prefix", action="store_true",
                    help="prefix keys with 'module.' like the reference's "
                         "DDP-wrapped saves")
    args = ap.parse_args(argv)

    from simclr_tpu.eval import load_model_variables

    checkpoints = list_checkpoints_or_raise(args.target_dir)
    os.makedirs(args.out_dir, exist_ok=True)
    written = []
    for ckpt in checkpoints:
        variables = load_model_variables(ckpt)
        path = os.path.join(args.out_dir, os.path.basename(ckpt) + ".pt")
        save_torch_checkpoint(
            path, variables, args.base_cnn, args.kind, args.ddp_prefix
        )
        print(path)
        written.append(path)
    return written


if __name__ == "__main__":
    main()
