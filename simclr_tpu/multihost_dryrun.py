"""Multi-host dryrun WORKER: rendezvous + sharded residency + chunked ring.

One JAX process of a (possibly) multi-process job. Run under
``simclr_tpu.launch`` (which exports the ``JAX_COORDINATOR_ADDRESS`` /
``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` rendezvous convention) or
standalone as the single-process reference. The driver is
``scripts/multihost_dryrun.py``; this module is the payload it launches on
both sides of the parity comparison.

What it exercises, deliberately end to end:

  1. ``maybe_initialize_multihost`` — the real rendezvous path (honoring
     ``JAX_COORDINATOR_TIMEOUT_S`` so a wedged coordinator fails fast);
  2. ``mesh.put_row_sharded`` — the epoch-compile residency upload; the
     worker reports how many rows this process actually addresses, so the
     driver can assert each host feeds ONLY its local mesh rows;
  3. ``compress.grad_allreduce(..., overlap="chunked")`` — the chunked
     ppermute ring across the full global mesh, int8 wire format, with the
     per-device key convention the train step uses.

The checksum depends only on LOGICAL axis indices and the shared PRNG key,
never on which process hosts which device — so a 2-process 4+4-device run
must reproduce the 1-process 8-device run bitwise. That is the parity the
``multihost_dryrun`` watcher stage asserts.

Prints exactly one JSON line from process 0:

    {"worker": "multihost_dryrun", "process_count": N, "n_devices": D,
     "checksum": ..., "local_rows": ..., "expected_local_rows": ...}
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from simclr_tpu.parallel import compress
from simclr_tpu.parallel.mesh import (
    DATA_AXIS,
    MeshSpec,
    create_mesh,
    put_row_sharded,
    shard_map,
)
from simclr_tpu.parallel.multihost import maybe_initialize_multihost

# dataset rows per data shard; small enough to run anywhere, large enough
# that a wrong row block changes the checksum
ROWS_PER_SHARD = 8
ROW_WIDTH = 16
COMM_CHUNKS = 3  # non-divisible into the flat length: exercises the tail


def run() -> dict:
    maybe_initialize_multihost()
    mesh = create_mesh(MeshSpec(data=-1, model=1))
    n_data = mesh.shape[DATA_AXIS]

    # deterministic "dataset": row r is r, r+1, ... — any misrouted block
    # shifts the per-shard sums and breaks parity
    n_rows = ROWS_PER_SHARD * n_data
    rows = (
        np.arange(n_rows * ROW_WIDTH, dtype=np.float32).reshape(n_rows, ROW_WIDTH)
        / n_rows
    )
    resident = put_row_sharded(rows, mesh)
    local_rows = sum(s.data.shape[0] for s in resident.addressable_shards)
    expected_local = ROWS_PER_SHARD * len(
        [d for d in mesh.devices.flat if d.process_index == jax.process_index()]
    )

    def body(local_block):
        # a gradient-shaped vector built from THIS shard's resident rows and
        # logical index — physical device/process placement cancels out
        i = jax.lax.axis_index(DATA_AXIS)
        g = jnp.sum(local_block) * jnp.linspace(
            -1.0, 1.0, 257, dtype=jnp.float32
        ) + 0.01 * i.astype(jnp.float32)
        key = jax.random.fold_in(jax.random.key(0), i)
        out = compress.grad_allreduce(
            {"g": g}, DATA_AXIS, "int8",
            key=jax.random.fold_in(key, compress.KEY_FOLD_QUANT),
            overlap="chunked", chunks=COMM_CHUNKS,
        )["g"]
        return jnp.sum(out)[None]

    fn = jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P(DATA_AXIS)
        )
    )
    per_shard = fn(resident)
    # per-shard sums of a replica-identical result; fetch this process's
    # addressable piece and psum on host via the replicated total
    checksum = float(jnp.sum(per_shard.addressable_shards[0].data))
    total = float(
        np.sum([np.asarray(s.data).sum() for s in per_shard.addressable_shards])
    )
    return {
        "worker": "multihost_dryrun",
        "process_count": jax.process_count(),
        "n_devices": jax.device_count(),
        # replica-identical ring output => every shard sums the same reduced
        # vector, so shard 0's sum IS the global checksum on every process
        "checksum": checksum,
        "local_total": total,
        "local_rows": int(local_rows),
        "expected_local_rows": int(expected_local),
    }


def main() -> None:
    result = run()
    if jax.process_index() == 0:
        print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
