"""Multi-process launcher — the TPU-native analogue of the reference's
vendored ``launch.py``.

The reference spawns one process PER GPU and wires NCCL rendezvous env vars
into each (``/root/reference/launch.py:202-259``). Under SPMD there is one
process per HOST, so this launcher exists for the two situations where
something must start those host processes:

  * **Local multi-process testing** (``--nprocs N``): spawns N processes on
    this machine, each a full JAX distributed participant with its own block
    of virtual CPU devices — the only way to exercise true multi-PROCESS
    semantics (``jax.make_array_from_process_local_data``, per-host input
    sharding, cross-process collectives over the distributed runtime) without
    a real multi-host slice. An 8-device single-process mesh cannot cover
    this: it has one address space and one input pipeline.
  * **Unmanaged multi-host launch** (``--proc-id I --nprocs N --coordinator
    HOST:PORT``): runs the training module in-process on each host of a
    cluster that lacks auto-discovery (no Cloud TPU metadata, no SLURM).

Reference behaviors kept (they are launcher API, not NCCL details):
  * fail-fast: wait on children, kill survivors and raise on the first
    nonzero exit (``launch.py:255-259``);
  * ``OMP_NUM_THREADS=1`` guard when spawning >1 process per machine
    (``launch.py:216-223``);
  * pass-through of the training module and its Hydra-style overrides:
    ``python -m simclr_tpu.launch --nprocs 2 -m simclr_tpu.main
    parameter.epochs=1 ...``.

Rendezvous uses the JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID convention consumed by
:func:`simclr_tpu.parallel.multihost.maybe_initialize_multihost`.
"""

from __future__ import annotations

import argparse
import os
import runpy
import signal
import subprocess
import sys
import time


def _parse_args(argv: list[str] | None = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="python -m simclr_tpu.launch",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--nprocs", type=int, default=1,
        help="total number of JAX processes (hosts) in the job",
    )
    parser.add_argument(
        "--proc-id", type=int, default=None,
        help="this host's process id (multi-host mode; omit to spawn all "
        "processes locally)",
    )
    parser.add_argument(
        "--coordinator", default="127.0.0.1:12321",
        help="coordinator HOST:PORT (process 0's address in multi-host mode)",
    )
    parser.add_argument(
        "--devices-per-proc", type=int, default=None,
        help="virtual CPU devices per process (local testing mode; forces "
        "JAX_PLATFORMS=cpu and --xla_force_host_platform_device_count)",
    )
    parser.add_argument(
        "-m", dest="module", required=True,
        help="training module to run (e.g. simclr_tpu.main)",
    )
    parser.add_argument(
        "overrides", nargs="*",
        help="dotted config overrides passed through to the module",
    )
    return parser.parse_args(argv)


def _child_env(args: argparse.Namespace, proc_id: int) -> dict[str, str]:
    env = dict(os.environ)
    env["JAX_COORDINATOR_ADDRESS"] = args.coordinator
    env["JAX_NUM_PROCESSES"] = str(args.nprocs)
    env["JAX_PROCESS_ID"] = str(proc_id)
    if args.devices_per_proc:
        env["JAX_PLATFORMS"] = "cpu"
        flag = f"--xla_force_host_platform_device_count={args.devices_per_proc}"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flag).strip()
    if args.proc_id is None and args.nprocs > 1 and "OMP_NUM_THREADS" not in env:
        # reference launch.py:216-223 — avoid N processes x all cores. The
        # guard is per-MACHINE: it applies to local spawn mode only; a
        # --proc-id multi-host launch runs one process per machine
        env["OMP_NUM_THREADS"] = "1"
    return env


def main(argv: list[str] | None = None) -> None:
    args = _parse_args(argv)
    if args.nprocs < 1:
        raise SystemExit("--nprocs must be >= 1")
    if args.devices_per_proc is not None and args.devices_per_proc < 1:
        raise SystemExit("--devices-per-proc must be >= 1")

    if args.proc_id is not None:
        # multi-host mode: become the training module on this host
        os.environ.update(_child_env(args, args.proc_id))
        sys.argv = [args.module] + list(args.overrides)
        runpy.run_module(args.module, run_name="__main__", alter_sys=True)
        return

    # local mode: spawn every process here. Spawning INSIDE the try keeps a
    # mid-spawn interrupt or Popen failure from orphaning children already
    # started (they would block in rendezvous forever waiting for peers).
    cmd = [sys.executable, "-m", args.module] + list(args.overrides)
    children: list[subprocess.Popen] = []
    try:
        for i in range(args.nprocs):
            children.append(subprocess.Popen(cmd, env=_child_env(args, i)))
        # poll ALL children: an ordered wait() would miss a crash of child k
        # while child 0 blocks in a collective waiting for it, hanging the
        # job instead of failing fast
        failed_rc: int | None = None
        while failed_rc is None and any(c.poll() is None for c in children):
            for child in children:
                rc = child.poll()
                if rc is not None and rc != 0:
                    failed_rc = rc
                    break
            else:
                time.sleep(0.2)
        if failed_rc is None:
            failed_rc = next((c.returncode for c in children if c.returncode), None)
        if failed_rc is not None:
            for child in children:
                if child.poll() is None:
                    child.send_signal(signal.SIGTERM)
            for child in children:
                child.wait()
            raise subprocess.CalledProcessError(failed_rc, cmd)
    except subprocess.CalledProcessError:
        raise  # children already reaped above
    except BaseException:  # interrupt or spawn failure: no orphans
        for child in children:
            if child.poll() is None:
                child.send_signal(signal.SIGTERM)
        raise


if __name__ == "__main__":
    main()
