from simclr_tpu.ops.lars import lars, scale_by_larc, simclr_weight_decay_mask
from simclr_tpu.ops.ntxent import (
    ntxent_loss,
    ntxent_loss_local_negatives,
    ntxent_loss_sharded_rows,
)

__all__ = [
    "lars",
    "scale_by_larc",
    "simclr_weight_decay_mask",
    "ntxent_loss",
    "ntxent_loss_local_negatives",
    "ntxent_loss_sharded_rows",
]
