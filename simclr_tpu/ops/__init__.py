from simclr_tpu.ops.lars import (
    get_weight_decay_mask,
    lars,
    reference_weight_decay_mask,
    scale_by_larc,
    simclr_weight_decay_mask,
)
from simclr_tpu.ops.ntxent import (
    gather_global_candidates,
    ntxent_loss,
    ntxent_loss_local_negatives,
    ntxent_loss_sharded_rows,
)
from simclr_tpu.ops.ntxent_pallas import (
    masked_lse_pair,
    ntxent_loss_fused,
    ntxent_loss_fused_sharded,
)
from simclr_tpu.ops.ntxent_ring import ntxent_loss_ring

__all__ = [
    "lars",
    "scale_by_larc",
    "simclr_weight_decay_mask",
    "reference_weight_decay_mask",
    "get_weight_decay_mask",
    "gather_global_candidates",
    "ntxent_loss",
    "ntxent_loss_local_negatives",
    "ntxent_loss_sharded_rows",
    "masked_lse_pair",
    "ntxent_loss_fused",
    "ntxent_loss_fused_sharded",
    "ntxent_loss_ring",
]
