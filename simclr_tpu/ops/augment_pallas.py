"""Pallas TPU kernel: fused two-view SimCLR augmentation in one VMEM pass.

The XLA augmentation path (``data/augment.py`` vmapped per example) is
correct but traffic-heavy: each view materializes the per-example bilinear
weight matrices and several batch-sized float32 temporaries in HBM, and the
uint8 source rows are re-read per view — measured at 2.2 ms for 1024 images
(~7% of the step, docs/PERF.md). This kernel reads a tile of resident
**uint8** rows into VMEM once and emits BOTH augmented float32 views in a
single pass: in-VMEM dequant (``to_float`` semantics, uint8 never touches
HBM as float), the two bilinear crop/resize contractions, horizontal flip,
the random-order color jitter, and grayscale — no per-stage HBM
intermediates. Same discipline as ``ops/ntxent_pallas.py``: keep the hot
tensor in VMEM, never round-trip HBM.

Randomness stays single-sourced and bit-identical to the XLA path: every
stochastic parameter (crop box, flip/jitter/grayscale gates, jitter factors
and op order) is sampled OUTSIDE the kernel by the exact samplers the XLA
path uses — ``_view_keys`` → ``_sample_crop_box`` / ``jitter_params`` in
``data/augment.py``, consumed in the same key order — so the distribution
tests keep measuring the one true sampler and a knob flip changes the
schedule, not the draw. The kernel is a pure deterministic function of
(uint8 tile, per-view parameter rows).

The bilinear weights are rebuilt in-VMEM from the 4 crop-box scalars via
iota comparisons (equal to ``_axis_resize_weights``' scatter-add form,
including the clipped ``i0 == i1`` edge where both taps land on one column
and sum to 1), so the kernel's inputs per view are just
``(batch, _N_PARAMS)`` floats instead of ``(batch, out, H)+(batch, out, W)``
weight tensors.

Runs compiled on TPU; everywhere else (CPU tests) falls back to
``interpret=True`` automatically, exactly like ``ntxent_pallas``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from simclr_tpu.data import augment as _aug

# runtime.augment_impl universe — config validation and the builders both
# import this tuple so the error message and the dispatch can't drift
AUGMENT_IMPLS = ("xla", "fused")

# per-view parameter row: crop box (top, left, h, w) + flip/apply/gray gates
# + jitter factors (brightness, contrast, saturation, hue) + the 4-slot op
# order (the _JITTER_PERMS row, exact small ints in float32)
_N_PARAMS = 15


def validate_impl(impl: str) -> str:
    if impl not in AUGMENT_IMPLS:
        raise ValueError(
            f"augment_impl must be {'|'.join(AUGMENT_IMPLS)}, got {impl!r}"
        )
    return impl


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _tile_and_pad(n: int) -> tuple[int, int]:
    """(tile, padded_n) over the batch axis.

    Small batches round up to a multiple of 8 so one tile covers everything;
    large batches tile at 32 rows (≈0.4 MiB of uint8 source + ≈3 MiB of f32
    working set per view — comfortably inside VMEM with both views live).
    Padded tail rows carry zero parameter rows (a degenerate but finite
    crop) and are sliced off after the call.
    """
    tile = 32 if n >= 32 else -(-n // 8) * 8
    return tile, -(-n // tile) * tile


def _pad_rows(x: jnp.ndarray, n_pad: int) -> jnp.ndarray:
    n = x.shape[0]
    if n == n_pad:
        return x
    pad_widths = [(0, n_pad - n)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad_widths)


# ---------------------------------------------------------------------------
# parameter precompute (plain JAX, outside the kernel)
# ---------------------------------------------------------------------------

def _view_params(
    keys: jnp.ndarray, height: int, width: int, strength: float
) -> jnp.ndarray:
    """(n, _N_PARAMS) float32 parameter rows for one view.

    Consumes each per-view key exactly as ``simclr_augment_single`` does —
    ``_view_keys`` then crop box / flip gate / apply gate / jitter params /
    grayscale gate — through module-attribute lookups, so monkeypatched spy
    tests observe the same sampler calls the XLA path makes.
    """

    def one(key):
        k_crop, k_flip, k_apply, k_jitter, k_gray = _aug._view_keys(key)
        top, left, crop_h, crop_w = _aug._sample_crop_box(k_crop, height, width)
        flip = jax.random.uniform(k_flip) < _aug._HFLIP_P
        apply = jax.random.uniform(k_apply) < _aug._JITTER_APPLY_P
        f_b, f_c, f_s, f_h, perm_idx = _aug.jitter_params(k_jitter, strength)
        gray = jax.random.uniform(k_gray) < _aug._GRAYSCALE_P
        perm = jnp.asarray(_aug._JITTER_PERMS)[perm_idx].astype(jnp.float32)
        head = jnp.stack(
            [
                top, left, crop_h, crop_w,
                flip.astype(jnp.float32),
                apply.astype(jnp.float32),
                f_b, f_c, f_s, f_h,
                gray.astype(jnp.float32),
            ]
        ).astype(jnp.float32)
        return jnp.concatenate([head, perm])

    return jax.vmap(one)(keys)


# ---------------------------------------------------------------------------
# in-kernel ops (batched over the tile axis, all VMEM-resident)
# ---------------------------------------------------------------------------

def _axis_weights(origin, size, out_size: int, in_size: int) -> jnp.ndarray:
    """(tile, out_size, in_size) bilinear weights from per-row box scalars.

    Comparison form of ``augment._axis_resize_weights``' scatter-add: both
    taps are written via iota equality, so the clipped ``i0 == i1`` border
    case sums the two taps into one column exactly like ``.at[].add`` does.
    Index values are small exact integers in float32, so ``==`` is exact.
    """
    tn = origin.shape[0]
    dst = jax.lax.broadcasted_iota(jnp.float32, (tn, out_size), 1)
    centers = origin[:, None] + (dst + 0.5) * (size[:, None] / out_size) - 0.5
    centers = jnp.clip(
        centers, origin[:, None], origin[:, None] + size[:, None] - 1.0
    )
    floor = jnp.floor(centers)
    frac = centers - floor
    i0 = jnp.clip(floor, 0.0, in_size - 1.0)
    i1 = jnp.clip(i0 + 1.0, 0.0, in_size - 1.0)
    src = jax.lax.broadcasted_iota(jnp.float32, (tn, out_size, in_size), 2)
    return (src == i0[..., None]).astype(jnp.float32) * (
        1.0 - frac[..., None]
    ) + (src == i1[..., None]).astype(jnp.float32) * frac[..., None]


def _luma(img: jnp.ndarray) -> jnp.ndarray:
    w = _aug._GRAY_WEIGHTS  # ITU-R 601, the XLA path's constants
    return img[..., 0] * w[0] + img[..., 1] * w[1] + img[..., 2] * w[2]


def _gray3(img: jnp.ndarray) -> jnp.ndarray:
    return _luma(img)[..., None] * jnp.ones((3,), jnp.float32)


def _brightness(img, f):
    return jnp.clip(img * f, 0.0, 1.0)


def _contrast(img, f):
    # per-example mean of the grayscale image (augment.adjust_contrast
    # semantics, batched over the tile axis)
    mean = _luma(img).mean(axis=(1, 2)).reshape(-1, 1, 1, 1)
    return jnp.clip(mean + f * (img - mean), 0.0, 1.0)


def _saturation(img, f):
    g = _gray3(img)
    return jnp.clip(g + f * (img - g), 0.0, 1.0)


def _augment_tile(x, p, out_size: int, height: int, width: int):
    """Both-crop-to-gray chain for one view over one VMEM tile.

    ``x``: (tile, H, W, 3) float32 in [0, 1]; ``p``: (tile, _N_PARAMS).
    Mirrors ``simclr_augment_single`` stage for stage; the per-example
    ``lax.switch`` over jitter ops becomes compute-all-and-select, which is
    what vmap lowers the switch to anyway.
    """
    tn = x.shape[0]
    w_rows = _axis_weights(p[:, 0], p[:, 2], out_size, height)
    w_cols = _axis_weights(p[:, 1], p[:, 3], out_size, width)
    y = jnp.einsum(
        "toh,thwc->towc", w_rows, x, preferred_element_type=jnp.float32
    )
    y = jnp.einsum(
        "tpw,towc->topc", w_cols, y, preferred_element_type=jnp.float32
    )
    flip = p[:, 4].reshape(tn, 1, 1, 1) > 0.5
    y = jnp.where(flip, jnp.flip(y, axis=2), y)

    f_b = p[:, 6].reshape(tn, 1, 1, 1)
    f_c = p[:, 7].reshape(tn, 1, 1, 1)
    f_s = p[:, 8].reshape(tn, 1, 1, 1)
    f_h = p[:, 9].reshape(tn, 1, 1)
    jit = y
    for slot in range(4):
        op = p[:, 11 + slot].reshape(tn, 1, 1, 1)
        jit = jnp.where(
            op == 0.0,
            _brightness(jit, f_b),
            jnp.where(
                op == 1.0,
                _contrast(jit, f_c),
                jnp.where(
                    op == 2.0,
                    _saturation(jit, f_s),
                    _aug.adjust_hue(jit, f_h),
                ),
            ),
        )
    apply = p[:, 5].reshape(tn, 1, 1, 1) > 0.5
    y = jnp.where(apply, jit, y)
    gray = p[:, 10].reshape(tn, 1, 1, 1) > 0.5
    return jnp.where(gray, _gray3(y), y)


def _augment_kernel(*refs, out_size, height, width, scale, n_views):
    """Grid step: one batch tile. Refs: n_views param blocks, the image
    block, then n_views output blocks. The source tile is loaded and
    dequantized ONCE (``scale`` = 1/255 for uint8 inputs — this is where
    ``to_float`` happens, in VMEM); every view reads the same registers.
    """
    img_ref = refs[n_views]
    outs = refs[n_views + 1:]
    x = img_ref[:].astype(jnp.float32) * scale
    for v in range(n_views):
        outs[v][:] = _augment_tile(x, refs[v][:], out_size, height, width)


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------

def _fused_views(images, keys_per_view, strength: float, out_size: int):
    n, height, width, channels = images.shape
    if channels != 3:
        raise ValueError(
            f"fused augmentation expects RGB (N, H, W, 3), got {images.shape}"
        )
    scale = 1.0 / 255.0 if images.dtype == jnp.uint8 else 1.0
    if images.dtype != jnp.uint8:
        images = images.astype(jnp.float32)
    params = [
        _view_params(k, height, width, strength) for k in keys_per_view
    ]
    tn, n_pad = _tile_and_pad(n)
    imgs = _pad_rows(images, n_pad)
    params = [_pad_rows(p, n_pad) for p in params]
    n_views = len(params)
    kernel = functools.partial(
        _augment_kernel,
        out_size=out_size,
        height=height,
        width=width,
        scale=scale,
        n_views=n_views,
    )
    views = pl.pallas_call(
        kernel,
        grid=(n_pad // tn,),
        in_specs=[pl.BlockSpec((tn, _N_PARAMS), lambda i: (i, 0))] * n_views
        + [pl.BlockSpec((tn, height, width, channels), lambda i: (i, 0, 0, 0))],
        out_specs=[
            pl.BlockSpec(
                (tn, out_size, out_size, channels), lambda i: (i, 0, 0, 0)
            )
        ]
        * n_views,
        out_shape=[
            jax.ShapeDtypeStruct(
                (n_pad, out_size, out_size, channels), jnp.float32
            )
        ]
        * n_views,
        interpret=_interpret(),
    )(*params, imgs)
    return tuple(v[:n] for v in views)


def fused_two_views(
    rng: jax.Array,
    images: jnp.ndarray,
    strength: float = 0.5,
    out_size: int = 32,
    *,
    keys: jax.Array | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Both SimCLR views of a uint8 (or float) batch in one VMEM pass.

    Key schedule is identical to ``steps._augment_two_views``' XLA path:
    ``split(rng, 2n)``, first half view 0, second half view 1 — so equal
    seeds draw bit-identical augmentation parameters on either impl. The
    training step passes precomputed ``keys`` (same (2n,) layout) so the
    per-sample streams can be derived from GLOBAL batch position instead
    (layout-invariant across elastic remeshes, see
    ``steps._global_sample_keys``); ``rng`` is ignored then.
    """
    n = images.shape[0]
    if keys is None:
        keys = jax.random.split(rng, 2 * n)
    v0, v1 = _fused_views(images, (keys[:n], keys[n:]), strength, out_size)
    return v0, v1


def fused_one_view(
    rng: jax.Array,
    images: jnp.ndarray,
    strength: float = 0.5,
    out_size: int = 32,
    *,
    keys: jax.Array | None = None,
) -> jnp.ndarray:
    """Single augmented view (the supervised baseline's consumption —
    ``split(rng, n)``, same key schedule as its XLA path; ``keys``
    overrides the schedule exactly as in :func:`fused_two_views`)."""
    n = images.shape[0]
    if keys is None:
        keys = jax.random.split(rng, n)
    (view,) = _fused_views(images, (keys,), strength, out_size)
    return view
