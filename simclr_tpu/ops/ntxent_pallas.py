"""Pallas TPU kernel: fused blockwise NT-Xent logsumexp (flash-style).

At pod-scale global batches the NT-Xent hot spot is the (2N)x(2N) similarity
matrix: XLA materializes it in HBM twice (forward logits + backward softmax),
making the loss HBM-bandwidth-bound at ~(2N)^2 x 4 bytes per direction. This
kernel never materializes it: similarity tiles are computed on the MXU from
VMEM-resident embedding blocks and immediately folded into a running
(online-softmax) logsumexp — the same trick flash attention uses for the
attention matrix, applied to the contrastive candidate axis (SURVEY §7.8).

Structure:
  * forward — grid (row_tiles, col_tiles), col innermost; per row-tile
    scratch holds running max/sum; self-similarity masked by global index;
    one (M,1) logsumexp vector written out.
  * backward — softmax tiles are recomputed from the saved logsumexp and
    folded straight into the two gradient contractions (anchor rows and
    candidate columns of the symmetric similarity), each its own kernel with
    a VMEM accumulator. Peak memory stays O(M·d + TM·TN).
  * :func:`ntxent_loss_fused` — drop-in equivalent of
    ``ntxent.ntxent_loss`` (mean reduction): normalization and the positive
    term stay in plain JAX (autodiffed), only the masked-logsumexp is custom.

Runs compiled on TPU; everywhere else (CPU tests) falls back to
``interpret=True`` automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from simclr_tpu.ops.ntxent import _l2_normalize

_NEG_INF = -1e9


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _tile_and_pad(m: int) -> tuple[int, int]:
    """(tile, padded_m): hardware-aligned tiling for any batch size.

    Rows are padded up to the tile so block shapes never fall below the TPU
    (8, 128) native tile; padded candidate columns are masked to -inf inside
    the kernels (flash-kernel style), so results are exact for the real m.
    """
    if m >= 128:
        tile = 128
    else:
        tile = -(-m // 8) * 8  # next multiple of 8: one tile covers everything
    return tile, -(-m // tile) * tile


def _pad_rows(x: jnp.ndarray, m_pad: int, fill: float = 0.0) -> jnp.ndarray:
    m = x.shape[0]
    if m == m_pad:
        return x
    pad_widths = [(0, m_pad - m)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad_widths, constant_values=fill)


# ---------------------------------------------------------------------------
# forward: masked row logsumexp of  z @ z.T / tau
# ---------------------------------------------------------------------------

def _lse_kernel(
    z_row_ref, z_col_ref, lse_ref, m_scr, s_scr, *, inv_temp, tm, tn, m_real
):
    i = pl.program_id(0)
    j = pl.program_id(1)

    sim = (
        jnp.dot(z_row_ref[:], z_col_ref[:].T, preferred_element_type=jnp.float32)
        * inv_temp
    )
    rows = jax.lax.broadcasted_iota(jnp.int32, (tm, tn), 0) + i * tm
    cols = jax.lax.broadcasted_iota(jnp.int32, (tm, tn), 1) + j * tn
    # mask self-similarity AND padded candidate columns
    sim = jnp.where((rows == cols) | (cols >= m_real), _NEG_INF, sim)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full((tm, 1), _NEG_INF, jnp.float32)
        s_scr[:] = jnp.zeros((tm, 1), jnp.float32)

    m_prev = m_scr[:]
    m_new = jnp.maximum(m_prev, sim.max(axis=1, keepdims=True))
    s_scr[:] = s_scr[:] * jnp.exp(m_prev - m_new) + jnp.exp(sim - m_new).sum(
        axis=1, keepdims=True
    )
    m_scr[:] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        lse_ref[:] = jnp.log(s_scr[:]) + m_scr[:]


def _masked_lse_fwd_impl(zn: jnp.ndarray, temperature: float) -> jnp.ndarray:
    m, d = zn.shape
    tile, m_pad = _tile_and_pad(m)
    zp = _pad_rows(zn, m_pad)
    kernel = functools.partial(
        _lse_kernel, inv_temp=1.0 / temperature, tm=tile, tn=tile, m_real=m
    )
    lse = pl.pallas_call(
        kernel,
        grid=(m_pad // tile, m_pad // tile),
        in_specs=[
            pl.BlockSpec((tile, d), lambda i, j: (i, 0)),
            pl.BlockSpec((tile, d), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((tile, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m_pad, 1), jnp.float32),
        scratch_shapes=[_vmem((tile, 1)), _vmem((tile, 1))],
        interpret=_interpret(),
    )(zp, zp)
    return lse[:m, 0]


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


# ---------------------------------------------------------------------------
# backward: dz = (diag(g) P + P.T diag(g)) @ z / tau, P never materialized
# ---------------------------------------------------------------------------

def _grad_kernel(
    z_out_ref, z_in_ref, lse_ref, g_ref, acc_ref, *, inv_temp, tm, tn, m_real,
    transpose,
):
    """Accumulate one output row-tile of the gradient.

    ``transpose=False``: output tile = anchor rows i; inner loop over
    candidate tiles j accumulates sum_j (g_i * P_ij) z_j.
    ``transpose=True``: output tile = candidate rows j; inner loop over
    anchor tiles i accumulates sum_i (g_i * P_ij) z_i, using sim symmetry.
    """
    o = pl.program_id(0)  # output tile index
    k = pl.program_id(1)  # reduction tile index

    sim = (
        jnp.dot(z_out_ref[:], z_in_ref[:].T, preferred_element_type=jnp.float32)
        * inv_temp
    )
    rows = jax.lax.broadcasted_iota(jnp.int32, (tm, tn), 0) + o * tm
    cols = jax.lax.broadcasted_iota(jnp.int32, (tm, tn), 1) + k * tn
    # mask the diagonal and padded reduction-axis entries (their lse/g pads
    # are finite zeros, so exp(sim - lse) would otherwise contribute)
    sim = jnp.where((rows == cols) | (cols >= m_real), _NEG_INF, sim)

    if transpose:
        # lse/g belong to the reduction (anchor) axis -> broadcast over cols
        w = jnp.exp(sim - lse_ref[:].reshape(1, tn)) * g_ref[:].reshape(1, tn)
    else:
        # lse/g belong to the output (anchor) axis -> broadcast over rows
        w = jnp.exp(sim - lse_ref[:].reshape(tm, 1)) * g_ref[:].reshape(tm, 1)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(w, z_in_ref[:], preferred_element_type=jnp.float32)


def _masked_lse_bwd_impl(
    zn: jnp.ndarray, lse: jnp.ndarray, g: jnp.ndarray, temperature: float
) -> jnp.ndarray:
    m, d = zn.shape
    tile, m_pad = _tile_and_pad(m)
    zp = _pad_rows(zn, m_pad)
    lse2 = _pad_rows(lse.reshape(m, 1), m_pad)           # pad value 0: finite
    g2 = _pad_rows(g.astype(jnp.float32).reshape(m, 1), m_pad)

    def call(transpose):
        kernel = functools.partial(
            _grad_kernel, inv_temp=1.0 / temperature, tm=tile, tn=tile,
            m_real=m, transpose=transpose,
        )
        # anchor-grad pass: lse/g indexed by output tile (o);
        # candidate-grad pass: lse/g indexed by reduction tile (k)
        stat_index = (lambda o, k: (k, 0)) if transpose else (lambda o, k: (o, 0))
        return pl.pallas_call(
            kernel,
            grid=(m_pad // tile, m_pad // tile),
            in_specs=[
                pl.BlockSpec((tile, d), lambda o, k: (o, 0)),
                pl.BlockSpec((tile, d), lambda o, k: (k, 0)),
                pl.BlockSpec((tile, 1), stat_index),
                pl.BlockSpec((tile, 1), stat_index),
            ],
            out_specs=pl.BlockSpec((tile, d), lambda o, k: (o, 0)),
            out_shape=jax.ShapeDtypeStruct((m_pad, d), jnp.float32),
            interpret=_interpret(),
        )(zp, zp, lse2, g2)

    # acc_ref IS the output block (revisited across k); no scratch needed
    danchor = call(transpose=False)
    dcandidate = call(transpose=True)
    return (danchor[:m] + dcandidate[:m]) / temperature


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _masked_lse(zn: jnp.ndarray, temperature: float) -> jnp.ndarray:
    """Row logsumexp of the self-masked similarity matrix (M,)."""
    return _masked_lse_fwd_impl(zn, temperature)


def _masked_lse_fwd(zn, temperature):
    lse = _masked_lse_fwd_impl(zn, temperature)
    return lse, (zn, lse)


def _masked_lse_bwd(temperature, res, g):
    zn, lse = res
    return (_masked_lse_bwd_impl(zn, lse, g, temperature),)


_masked_lse.defvjp(_masked_lse_fwd, _masked_lse_bwd)


def ntxent_loss_fused(
    z0: jnp.ndarray, z1: jnp.ndarray, temperature: float = 0.5
) -> jnp.ndarray:
    """Fused-kernel NT-Xent, numerically equal to ``ntxent_loss`` (mean).

    Normalization and the positive term run in plain JAX (cheap, autodiffed);
    the quadratic masked-logsumexp runs in the Pallas kernel with a custom
    VJP that recomputes softmax tiles instead of storing the matrix.
    """
    if z0.shape != z1.shape:
        raise ValueError(
            f"view embeddings must have identical shapes, got {z0.shape} vs {z1.shape}"
        )
    n = z0.shape[0]
    z = _l2_normalize(jnp.concatenate([z0, z1], axis=0))
    lse = _masked_lse(z, float(temperature))
    pos = jnp.sum(z * jnp.roll(z, n, axis=0), axis=-1) / temperature
    return (lse - pos).mean()
