"""Pallas TPU kernels: fused blockwise NT-Xent logsumexp (flash-style).

At pod-scale global batches the NT-Xent hot spot is the (anchors x
candidates) similarity matrix: XLA materializes it in HBM twice (forward
logits + backward softmax), making the loss HBM-bandwidth-bound. These
kernels never materialize it: similarity tiles are computed on the MXU from
VMEM-resident embedding blocks and immediately folded into a running
(online-softmax) logsumexp — the same trick flash attention uses for the
attention matrix, applied to the contrastive candidate axis (SURVEY §7.8).

The core op is RECTANGULAR: anchors (Ma, d) against candidates (Mc, d) with
a per-anchor ``self_idx`` column masked out. That covers both:
  * the single-device / local-negatives case — candidates == anchors,
    ``self_idx = arange`` (:func:`ntxent_loss_fused`);
  * the sharded global-negatives case — local anchors against the
    all-gathered global candidate set inside ``shard_map``
    (:func:`ntxent_loss_fused_sharded`), where gradients w.r.t. the gathered
    candidates flow back through the gather's transpose (a psum-scatter) to
    the owning shards automatically.

Structure:
  * forward — grid (row_tiles, col_tiles), col innermost; per row-tile
    scratch holds the running max/sum; one (Ma, 1) logsumexp vector out.
  * backward — softmax tiles are recomputed from the saved logsumexp and
    folded straight into two gradient contractions (anchor rows; candidate
    rows), each its own kernel accumulating into its output block. Peak
    memory stays O((Ma + Mc)·d + tile²).
  * both dims are padded to hardware-aligned tiles; padded candidates are
    masked to -inf, padded anchors are neutralized by zero cotangents.

Runs compiled on TPU; everywhere else (CPU tests) falls back to
``interpret=True`` automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from simclr_tpu.ops.ntxent import _l2_normalize, gather_global_candidates

_NEG_INF = -1e9


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


def _tile_and_pad(m: int) -> tuple[int, int]:
    """(tile, padded_m): hardware-aligned tiling for any batch size.

    Rows are padded up to the tile so block shapes never fall below the TPU
    (8, 128) native tile; padded candidate columns are masked to -inf inside
    the kernels (flash-kernel style), so results are exact for the real m.
    """
    if m >= 128:
        tile = 128
    else:
        tile = -(-m // 8) * 8  # next multiple of 8: one tile covers everything
    return tile, -(-m // tile) * tile


def _pad_rows(x: jnp.ndarray, m_pad: int, fill=0) -> jnp.ndarray:
    m = x.shape[0]
    if m == m_pad:
        return x
    pad_widths = [(0, m_pad - m)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad_widths, constant_values=fill)


# ---------------------------------------------------------------------------
# forward: per-anchor logsumexp of  A @ C.T / tau  with self columns masked
# ---------------------------------------------------------------------------

def _lse_kernel(
    self_ref, a_ref, c_ref, lse_ref, m_scr, s_scr, *, inv_temp, ta, tc, mc_real
):
    j = pl.program_id(1)

    sim = (
        jnp.dot(a_ref[:], c_ref[:].T, preferred_element_type=jnp.float32)
        * inv_temp
    )
    cols = jax.lax.broadcasted_iota(jnp.int32, (ta, tc), 1) + j * tc
    # mask each anchor's own column and the padded candidate tail
    sim = jnp.where((cols == self_ref[:]) | (cols >= mc_real), _NEG_INF, sim)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full((ta, 1), _NEG_INF, jnp.float32)
        s_scr[:] = jnp.zeros((ta, 1), jnp.float32)

    m_prev = m_scr[:]
    m_new = jnp.maximum(m_prev, sim.max(axis=1, keepdims=True))
    s_scr[:] = s_scr[:] * jnp.exp(m_prev - m_new) + jnp.exp(sim - m_new).sum(
        axis=1, keepdims=True
    )
    m_scr[:] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        lse_ref[:] = jnp.log(s_scr[:]) + m_scr[:]


def _lse_fwd_impl(anchors, candidates, self_idx, temperature):
    ma, d = anchors.shape
    mc = candidates.shape[0]
    ta, ma_pad = _tile_and_pad(ma)
    tc, mc_pad = _tile_and_pad(mc)
    ap = _pad_rows(anchors, ma_pad)
    cp = _pad_rows(candidates, mc_pad)
    sp = _pad_rows(self_idx.astype(jnp.int32).reshape(ma, 1), ma_pad, fill=-1)

    kernel = functools.partial(
        _lse_kernel, inv_temp=1.0 / temperature, ta=ta, tc=tc, mc_real=mc
    )
    lse = pl.pallas_call(
        kernel,
        grid=(ma_pad // ta, mc_pad // tc),
        in_specs=[
            pl.BlockSpec((ta, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((ta, d), lambda i, j: (i, 0)),
            pl.BlockSpec((tc, d), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((ta, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((ma_pad, 1), jnp.float32),
        scratch_shapes=[_vmem((ta, 1)), _vmem((ta, 1))],
        interpret=_interpret(),
    )(sp, ap, cp)
    return lse[:ma, 0]


# ---------------------------------------------------------------------------
# backward: dA_i = sum_j g_i P_ij C_j / tau ;  dC_j = sum_i g_i P_ij A_i / tau
# with P_ij = exp(sim_ij - lse_i), recomputed tile-by-tile
# ---------------------------------------------------------------------------

def _danchor_kernel(
    self_ref, a_ref, c_ref, lse_ref, g_ref, acc_ref, *, inv_temp, ta, tc, mc_real
):
    """Output tile: anchor rows; reduction over candidate tiles (inner)."""
    k = pl.program_id(1)
    sim = (
        jnp.dot(a_ref[:], c_ref[:].T, preferred_element_type=jnp.float32)
        * inv_temp
    )
    cols = jax.lax.broadcasted_iota(jnp.int32, (ta, tc), 1) + k * tc
    sim = jnp.where((cols == self_ref[:]) | (cols >= mc_real), _NEG_INF, sim)
    w = jnp.exp(sim - lse_ref[:]) * g_ref[:]  # lse/g broadcast over columns

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(w, c_ref[:], preferred_element_type=jnp.float32)


def _dcandidate_kernel(
    self_ref, c_ref, a_ref, lse_ref, g_ref, acc_ref, *, inv_temp, tc, ta, mc_real
):
    """Output tile: candidate rows; reduction over anchor tiles (inner).

    ``self_ref``/``lse_ref``/``g_ref`` are blocks of the ANCHOR (reduction)
    axis; the self-mask triggers where the candidate row equals the anchor's
    self column.
    """
    o = pl.program_id(0)
    sim = (
        jnp.dot(c_ref[:], a_ref[:].T, preferred_element_type=jnp.float32)
        * inv_temp
    )  # (tc, ta): rows = candidates, cols = anchors
    rows = jax.lax.broadcasted_iota(jnp.int32, (tc, ta), 0) + o * tc
    sim = jnp.where(
        (rows == self_ref[:].reshape(1, ta)) | (rows >= mc_real), _NEG_INF, sim
    )
    w = jnp.exp(sim - lse_ref[:].reshape(1, ta)) * g_ref[:].reshape(1, ta)

    @pl.when(pl.program_id(1) == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(w, a_ref[:], preferred_element_type=jnp.float32)


def _lse_bwd_impl(anchors, candidates, self_idx, lse, g, temperature):
    ma, d = anchors.shape
    mc = candidates.shape[0]
    ta, ma_pad = _tile_and_pad(ma)
    tc, mc_pad = _tile_and_pad(mc)
    ap = _pad_rows(anchors, ma_pad)
    cp = _pad_rows(candidates, mc_pad)
    sp = _pad_rows(self_idx.astype(jnp.int32).reshape(ma, 1), ma_pad, fill=-1)
    lp = _pad_rows(lse.reshape(ma, 1), ma_pad)          # pad 0: finite
    gp = _pad_rows(g.astype(jnp.float32).reshape(ma, 1), ma_pad)  # pad 0: inert

    da = pl.pallas_call(
        functools.partial(
            _danchor_kernel, inv_temp=1.0 / temperature, ta=ta, tc=tc, mc_real=mc
        ),
        grid=(ma_pad // ta, mc_pad // tc),
        in_specs=[
            pl.BlockSpec((ta, 1), lambda o, k: (o, 0)),
            pl.BlockSpec((ta, d), lambda o, k: (o, 0)),
            pl.BlockSpec((tc, d), lambda o, k: (k, 0)),
            pl.BlockSpec((ta, 1), lambda o, k: (o, 0)),
            pl.BlockSpec((ta, 1), lambda o, k: (o, 0)),
        ],
        out_specs=pl.BlockSpec((ta, d), lambda o, k: (o, 0)),
        out_shape=jax.ShapeDtypeStruct((ma_pad, d), jnp.float32),
        interpret=_interpret(),
    )(sp, ap, cp, lp, gp)

    dc = pl.pallas_call(
        functools.partial(
            _dcandidate_kernel, inv_temp=1.0 / temperature, tc=tc, ta=ta, mc_real=mc
        ),
        grid=(mc_pad // tc, ma_pad // ta),
        in_specs=[
            pl.BlockSpec((ta, 1), lambda o, k: (k, 0)),
            pl.BlockSpec((tc, d), lambda o, k: (o, 0)),
            pl.BlockSpec((ta, d), lambda o, k: (k, 0)),
            pl.BlockSpec((ta, 1), lambda o, k: (k, 0)),
            pl.BlockSpec((ta, 1), lambda o, k: (k, 0)),
        ],
        out_specs=pl.BlockSpec((tc, d), lambda o, k: (o, 0)),
        out_shape=jax.ShapeDtypeStruct((mc_pad, d), jnp.float32),
        interpret=_interpret(),
    )(sp, cp, ap, lp, gp)

    inv_t = 1.0 / temperature
    return da[:ma] * inv_t, dc[:mc] * inv_t


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def masked_lse_pair(anchors, candidates, self_idx, temperature):
    """Per-anchor logsumexp of ``anchors @ candidates.T / temperature`` with
    column ``self_idx[i]`` masked for anchor ``i``. Shape (Ma,)."""
    return _lse_fwd_impl(anchors, candidates, self_idx, temperature)


def _pair_fwd(anchors, candidates, self_idx, temperature):
    lse = _lse_fwd_impl(anchors, candidates, self_idx, temperature)
    return lse, (anchors, candidates, self_idx, lse)


def _pair_bwd(temperature, res, g):
    anchors, candidates, self_idx, lse = res
    da, dc = _lse_bwd_impl(anchors, candidates, self_idx, lse, g, temperature)
    dself = np.zeros(self_idx.shape, dtype=jax.dtypes.float0)
    return da, dc, dself


masked_lse_pair.defvjp(_pair_fwd, _pair_bwd)


# ---------------------------------------------------------------------------
# public losses
# ---------------------------------------------------------------------------

def ntxent_loss_fused(
    z0: jnp.ndarray, z1: jnp.ndarray, temperature: float = 0.5
) -> jnp.ndarray:
    """Fused-kernel NT-Xent, numerically equal to ``ntxent.ntxent_loss``
    (mean reduction). Candidates are the anchors themselves.

    Normalization and the positive term run in plain JAX (cheap, autodiffed);
    the quadratic masked-logsumexp runs in the Pallas kernel with a custom
    VJP that recomputes softmax tiles instead of storing the matrix.
    """
    if z0.shape != z1.shape:
        raise ValueError(
            f"view embeddings must have identical shapes, got {z0.shape} vs {z1.shape}"
        )
    n = z0.shape[0]
    z = _l2_normalize(jnp.concatenate([z0, z1], axis=0))
    lse = masked_lse_pair(z, z, jnp.arange(2 * n, dtype=jnp.int32), float(temperature))
    pos = jnp.sum(z * jnp.roll(z, n, axis=0), axis=-1) / temperature
    return (lse - pos).mean()


def ntxent_loss_fused_sharded(
    z0: jnp.ndarray,
    z1: jnp.ndarray,
    axis_name: str,
    temperature: float = 0.5,
) -> jnp.ndarray:
    """Global-negatives NT-Xent with the fused kernel, inside ``shard_map``.

    Same objective and candidate layout as
    ``ntxent.ntxent_loss_sharded_rows`` (all-gathered ``[all z0 | all z1]``
    candidates, local anchor rows), but the (2B_local x 2B_global)
    similarity block lives only in VMEM tiles. Gradients w.r.t. the gathered
    candidates flow back through the gather transpose (psum-scatter) to the
    owning shards.
    """
    z_local, candidates, self_idx, _pos_idx = gather_global_candidates(
        z0, z1, axis_name
    )
    lse = masked_lse_pair(z_local, candidates, self_idx, float(temperature))
    # positives are co-resident (z0_i and z1_i on the same shard): cheap
    # local row-wise dot instead of indexing the gathered set by _pos_idx
    n_local = z0.shape[0]
    pos = jnp.sum(z_local * jnp.roll(z_local, n_local, axis=0), axis=-1) / temperature
    return jax.lax.pmean((lse - pos).mean(), axis_name)
