"""LARS/LARC optimizer as optax gradient transformations.

The reference wraps SGD-momentum in Apex ``LARC(trust_coefficient=0.001,
clip=False)`` (``/root/reference/main.py:85-94``): per-parameter, an adaptive
factor ``trust * ||p|| / (||g|| + wd * ||p|| + eps)`` multiplies the
weight-decayed gradient, after which plain (non-Nesterov) momentum SGD runs
with its own weight decay disabled. Weight decay is masked off for biases and
batch-norm parameters (``exclude_from_wt_decay``,
``/root/reference/main.py:18-36``); the adaptive scaling itself applies to
*every* parameter, matching Apex LARC (which, unlike google-research LARS,
has no exclude-from-adaptation list).

Reproduced here as an optax chain so it composes with schedules and works
under ``jit``/GSPMD (norms of sharded params become cross-replica reductions
automatically).

Documented deviation: the reference's name-substring skip list ("bias", "bn")
misses torchvision's ``downsample.1`` batch-norms, so those *do* get weight
decay there; our structural mask (leaf name ``bias``/``scale``) excludes all
norm parameters uniformly, which is the documented intent.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from simclr_tpu.models.arch import CONVS_PER_BLOCK, DOWNSAMPLE_STAGES


def scale_by_larc(
    trust_coefficient: float = 0.001,
    weight_decay: float = 0.0,
    weight_decay_mask: Callable[[Any], Any] | None = None,
    eps: float = 1e-8,
) -> optax.GradientTransformation:
    """Apex-LARC (clip=False) gradient scaling + masked weight decay.

    For each parameter: ``g_out = (g + wd_p * p) * adaptive`` where
    ``adaptive = trust * ||p|| / (||g|| + wd_p * ||p|| + eps)`` if both norms
    are nonzero else 1, and ``wd_p`` is ``weight_decay`` where the mask is
    True else 0. Follow with momentum + lr scaling.
    """

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("scale_by_larc requires params")
        if weight_decay_mask is None:
            mask = jax.tree.map(lambda _: True, updates)
        else:
            mask = weight_decay_mask(params)

        def scale(g, p, use_wd):
            g = g.astype(jnp.float32)
            p = p.astype(jnp.float32)
            wd = weight_decay if use_wd else 0.0
            p_norm = jnp.linalg.norm(p)
            g_norm = jnp.linalg.norm(g)
            adaptive = trust_coefficient * p_norm / (g_norm + wd * p_norm + eps)
            # Apex only applies decay+scaling when BOTH norms are nonzero
            # (`if param_norm != 0 and grad_norm != 0`); a zero-grad param
            # must pass through untouched, not decay toward zero.
            active = (p_norm > 0.0) & (g_norm > 0.0)
            return jnp.where(active, (g + wd * p) * adaptive, g)

        updates = jax.tree.map(scale, updates, params, mask)
        return updates, state

    return optax.GradientTransformation(init_fn, update_fn)


def lars(
    learning_rate: float | optax.Schedule,
    trust_coefficient: float = 0.001,
    weight_decay: float = 0.0,
    weight_decay_mask: Callable[[Any], Any] | None = None,
    momentum: float = 0.9,
    eps: float = 1e-8,
) -> optax.GradientTransformation:
    """Full reference optimizer: LARC scaling -> momentum -> -lr.

    ``optax.trace(decay=momentum, nesterov=False)`` reproduces torch SGD's
    momentum buffer (``buf = m * buf + g``, update ``-lr * buf``).
    """
    return optax.chain(
        scale_by_larc(trust_coefficient, weight_decay, weight_decay_mask, eps),
        optax.trace(decay=momentum, nesterov=False),
        optax.scale_by_learning_rate(learning_rate),  # scales by -lr
    )


def reference_weight_decay_mask(params, base_cnn: str = "resnet18") -> Any:
    """The reference's ``("bias", "bn")`` name-substring skip rule
    (``/root/reference/main.py:18-36``) transcribed onto our tree — quirks
    included: torchvision's downsample batch-norm scale (torch name
    ``...downsample.1.weight``) and the projection head's batch-norm scale
    (``g.projection_head.1.weight``) contain neither substring, so the
    reference DOES weight-decay them. Biases never decay (every torch bias
    name contains "bias").

    For training-dynamics parity runs (tests/test_torch_dynamics.py);
    :func:`simclr_weight_decay_mask` remains the default documented intent.
    Select with ``optimizer.weight_decay_mask=reference``.
    """
    downsample_bn = f"BatchNorm_{CONVS_PER_BLOCK[base_cnn]}"
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def decide(path) -> bool:
        names = [str(p.key) for p in path if isinstance(p, jax.tree_util.DictKey)]
        leaf = names[-1]
        if leaf == "bias":
            return False
        if leaf == "scale":
            # f/<Block_i>/BatchNorm_{n_convs} is the projection-shortcut BN
            # (torch downsample.1); g/bn1 is the head BN — both decayed there
            if len(names) >= 3 and names[-2] == downsample_bn and names[0] == "f":
                return True
            return names[0] == "g" and names[-2] == "bn1"
        return True

    decisions = [decide(path) for path, _ in flat]

    # The substring rule keys off Flax auto-index names, so a rename or
    # reordering in resnet.py/heads.py would silently change which scales
    # decay (ADVICE r2). Pin the count structurally: one decayed scale per
    # projection-shortcut stage, plus the head BN iff the tree has one.
    def _leaf(path) -> str:
        return str(
            next(p.key for p in reversed(path) if isinstance(p, jax.tree_util.DictKey))
        )

    decayed_scales = sum(
        1 for (path, _), d in zip(flat, decisions) if d and _leaf(path) == "scale"
    )
    has_head = any(
        str(path[0].key) == "g"
        for path, _ in flat
        if path and isinstance(path[0], jax.tree_util.DictKey)
    )
    expected = DOWNSAMPLE_STAGES[base_cnn] + (1 if has_head else 0)
    if decayed_scales != expected:
        raise ValueError(
            f"reference_weight_decay_mask matched {decayed_scales} decayed norm "
            f"scales but {base_cnn} should have {expected} "
            f"({DOWNSAMPLE_STAGES[base_cnn]} projection-shortcut BNs"
            f"{' + head bn1' if has_head else ''}) — module naming drifted?"
        )
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, decisions)


def get_weight_decay_mask(kind: str, base_cnn: str = "resnet18") -> Callable[[Any], Any]:
    """Mask selection for the ``optimizer.weight_decay_mask`` config key:
    ``structural`` (default, documented intent) or ``reference`` (the torch
    substring rule, quirks included — for exact-recipe parity runs)."""
    if kind == "structural":
        return simclr_weight_decay_mask
    if kind == "reference":
        return lambda params: reference_weight_decay_mask(params, base_cnn)
    raise ValueError(
        f"optimizer.weight_decay_mask must be structural|reference, got {kind!r}"
    )


def simclr_weight_decay_mask(params) -> Any:
    """True where weight decay applies: everything except biases and norm
    scales — the reference's ("bias", "bn") skip list by structure rather
    than name substring (see module docstring for the deviation).
    """
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def decide(path) -> bool:
        leaf_name = None
        for part in reversed(path):
            if isinstance(part, jax.tree_util.DictKey):
                leaf_name = str(part.key)
                break
        return leaf_name not in ("bias", "scale")

    decisions = [decide(path) for path, _ in flat]
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, decisions)
