"""Ring NT-Xent: global negatives streamed over ICI, memory-flat.

The gathered-candidates loss (``ntxent.ntxent_loss_sharded_rows``) holds the
full (2·B_global, d) candidate matrix on every chip. At pod-scale global
batches that matrix — and the (2·B_local, 2·B_global) similarity block —
stops fitting comfortably in HBM/VMEM. This module is the contrastive
analogue of ring attention (SURVEY §5.7): candidate blocks circulate around
the data-axis ring via ``lax.ppermute`` while each chip maintains a running
(online-softmax) logsumexp over everything it has seen. Peak memory is
O(B_local·d + B_local²) regardless of ring size; total communication equals
one all-gather but is spread across steps XLA can overlap with the matmuls.

Correctness invariants (tested against the gathered implementation):
  * positives are always co-resident — z0_i and z1_i live on the same shard,
    so the positive similarity is computed locally before the ring spins;
  * self-similarity is masked only on ring step 0 (own block);
  * the online logsumexp update is exact, not approximate.

The backward pass is plain autodiff through ``lax.scan`` + ``ppermute``
(transpose of ppermute is the inverse permutation), so gradients also flow
around the ring without materializing the global candidate set.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from simclr_tpu.ops.ntxent import _l2_normalize
from simclr_tpu.parallel.mesh import axis_size

_NEG_INF = -1e9


def ntxent_loss_ring(
    z0: jnp.ndarray,
    z1: jnp.ndarray,
    axis_name: str,
    temperature: float = 0.5,
) -> jnp.ndarray:
    """Global-negatives NT-Xent with ring-streamed candidates.

    Must run inside ``shard_map``/``pmap`` over ``axis_name``. Returns the
    global mean loss (identical on every shard), exactly equal to
    ``ntxent_loss_sharded_rows`` up to float associativity.
    """
    n_local = z0.shape[0]
    n_shards = axis_size(axis_name)
    anchors = _l2_normalize(jnp.concatenate([z0, z1], axis=0))  # (2B, d)
    two_b = 2 * n_local

    # positive similarities: partner view, same shard (rows i <-> i+B)
    pos = jnp.sum(anchors * jnp.roll(anchors, n_local, axis=0), axis=-1) / temperature

    # ring permutation: each shard passes its block to the next shard.
    # The local (self-masked) block is folded in before the ring spins, so
    # exactly n_shards - 1 ppermutes happen — no wasted final rotation.
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    sim0 = (anchors @ anchors.T) / temperature  # own block, (2B, 2B)
    sim0 = jnp.where(jnp.eye(two_b, dtype=bool), _NEG_INF, sim0)
    m0 = sim0.max(axis=1)
    s0 = jnp.exp(sim0 - m0[:, None]).sum(axis=1)

    def ring_step(carry, _):
        block, m, s = carry  # block: (2B, d) visiting candidates
        block = lax.ppermute(block, axis_name, perm)
        sim = (anchors @ block.T) / temperature  # (2B, 2B) one MXU tile chain
        # exact online logsumexp accumulation
        m_new = jnp.maximum(m, sim.max(axis=1))
        s = s * jnp.exp(m - m_new) + jnp.exp(sim - m_new[:, None]).sum(axis=1)
        return (block, m_new, s), None

    (_, m, s), _ = lax.scan(
        ring_step, (anchors, m0, s0), None, length=n_shards - 1
    )

    per_anchor = (jnp.log(s) + m) - pos  # logsumexp - positive
    return lax.pmean(per_anchor.mean(), axis_name)
