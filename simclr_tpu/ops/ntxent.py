"""NT-Xent (normalized temperature-scaled cross-entropy) for TPU.

The algorithmic core of SimCLR, re-derived for XLA rather than translated:
the reference builds three masked similarity blocks with boolean-mask
compaction to N x (N-1) (``/root/reference/loss.py:42-52``) — a dynamic-shape
pattern XLA can't tile. We instead compute the full (2N)x(2N) similarity
matrix of the concatenated views, mask self-similarity additively (static
shapes, one MXU matmul), and take cross-entropy against the partner index.
For every anchor the candidate set is the same 2N-1 elements the reference
uses, so the losses are mathematically identical (verified in
tests/test_ntxent.py against an independent naive implementation).

Three entry points covering the reference + the TPU scaling axis (SURVEY §2.3):
  * :func:`ntxent_loss` — loss over whatever batch it is handed. Under a
    GSPMD ``jit`` with the batch sharded over the data axis this IS the
    global-negatives loss (XLA shards the matmul and inserts collectives).
  * :func:`ntxent_loss_sharded_rows` — explicit-collective version for use
    inside ``shard_map``: all-gathers the (small, N x d) embeddings over the
    data axis, computes only the local anchors' rows of the similarity
    matrix, and pmeans. Global negatives with O(local x global) memory.
  * :func:`ntxent_loss_local_negatives` — the reference's semantics: each
    replica sees only its own batch as negatives (negatives per sample =
    2*B_local - 2, ``/root/reference/loss.py:25-36``), kept as a config
    switch for parity experiments.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from simclr_tpu.parallel.mesh import axis_size

_NEG_INF = -1e9  # additive mask; safe in float32 logsumexp


def _l2_normalize(z: jnp.ndarray, eps: float = 1e-12) -> jnp.ndarray:
    z = z.astype(jnp.float32)
    return z / jnp.maximum(jnp.linalg.norm(z, axis=-1, keepdims=True), eps)


def _reduce(per_anchor: jnp.ndarray, reduction: str) -> jnp.ndarray:
    if reduction == "mean":
        return per_anchor.mean()
    if reduction == "sum":
        return per_anchor.sum()
    if reduction == "none":
        return per_anchor
    raise ValueError(f"reduction must be mean|sum|none, got {reduction!r}")


def _anchor_losses(
    anchors: jnp.ndarray,
    candidates: jnp.ndarray,
    self_idx: jnp.ndarray,
    pos_idx: jnp.ndarray,
    temperature: float,
) -> jnp.ndarray:
    """Per-anchor NT-Xent loss rows.

    anchors (M, d) and candidates (K, d) must be L2-normalized; ``self_idx``
    is each anchor's own column (masked out), ``pos_idx`` its positive's.
    """
    sim = (anchors @ candidates.T) / temperature  # (M, K) float32 on MXU
    m = anchors.shape[0]
    rows = jnp.arange(m)
    sim = sim.at[rows, self_idx].add(_NEG_INF)
    pos = sim[rows, pos_idx]
    return jax.nn.logsumexp(sim, axis=1) - pos


def ntxent_loss(
    z0: jnp.ndarray,
    z1: jnp.ndarray,
    temperature: float = 0.5,
    reduction: str = "mean",
) -> jnp.ndarray:
    """NT-Xent over the full batch given (both views, (N, d) each).

    ``reduction='mean'`` divides the summed two-view loss by 2N, matching the
    reference's mean semantics (``/root/reference/loss.py:65``). ``'none'``
    returns the (2N,) per-anchor vector, view-0 anchors first.
    """
    if z0.shape != z1.shape:
        raise ValueError(
            f"view embeddings must have identical shapes, got {z0.shape} vs {z1.shape}"
        )
    n = z0.shape[0]
    z = _l2_normalize(jnp.concatenate([z0, z1], axis=0))  # (2N, d)
    idx = jnp.arange(2 * n)
    pos_idx = (idx + n) % (2 * n)  # partner view is the positive
    per_anchor = _anchor_losses(z, z, idx, pos_idx, temperature)
    return _reduce(per_anchor, reduction)


def gather_global_candidates(
    z0: jnp.ndarray, z1: jnp.ndarray, axis_name: str
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Shared layout for gathered-negative losses, inside ``shard_map``.

    Returns ``(z_local, candidates, self_idx, pos_idx)``: normalized local
    anchors ``[z0_local | z1_local]``, the all-gathered candidate set
    ``[all z0 | all z1]``, and each local anchor's own / positive global
    column. Both the XLA (:func:`ntxent_loss_sharded_rows`) and Pallas-fused
    (``ntxent_pallas.ntxent_loss_fused_sharded``) losses consume exactly this
    layout — keep it single-sourced so their self-mask columns can never
    drift apart (their parity is test-asserted).
    """
    n_local = z0.shape[0]
    shard = jax.lax.axis_index(axis_name)
    n_shards = axis_size(axis_name)
    n_global = n_local * n_shards

    z_local = _l2_normalize(jnp.concatenate([z0, z1], axis=0))  # (2n_local, d)
    # gathered layout: [shard0 z0 | shard1 z0 | ... | shard0 z1 | shard1 z1 ...]
    g0 = jax.lax.all_gather(z_local[:n_local], axis_name, tiled=True)
    g1 = jax.lax.all_gather(z_local[n_local:], axis_name, tiled=True)
    candidates = jnp.concatenate([g0, g1], axis=0)  # (2*n_global, d)

    local_rows = jnp.arange(n_local)
    idx0 = shard * n_local + local_rows          # global cols of local view-0
    idx1 = n_global + idx0                       # global cols of local view-1
    self_idx = jnp.concatenate([idx0, idx1])
    pos_idx = jnp.concatenate([idx1, idx0])
    return z_local, candidates, self_idx, pos_idx


def ntxent_loss_sharded_rows(
    z0: jnp.ndarray,
    z1: jnp.ndarray,
    axis_name: str,
    temperature: float = 0.5,
) -> jnp.ndarray:
    """Global-negatives NT-Xent inside ``shard_map``/``pmap``.

    Gathers embeddings (cheap: activations, not params — SURVEY §5.7) over
    ``axis_name`` to form the global candidate set, but computes similarity
    rows only for local anchors. Returns the global mean loss (identical on
    every replica); gradients flow through the gather (its transpose is a
    psum-scatter, so each replica ends up with exactly its local grads).
    """
    z_local, candidates, self_idx, pos_idx = gather_global_candidates(
        z0, z1, axis_name
    )
    per_anchor = _anchor_losses(z_local, candidates, self_idx, pos_idx, temperature)
    # mean over ALL global anchors = pmean of local means
    return jax.lax.pmean(per_anchor.mean(), axis_name)


def ntxent_loss_local_negatives(
    z0: jnp.ndarray,
    z1: jnp.ndarray,
    axis_name: str | None = None,
    temperature: float = 0.5,
) -> jnp.ndarray:
    """Reference-parity NT-Xent: negatives restricted to the local replica.

    Inside ``shard_map`` each replica computes the loss on its own shard and
    the result is pmean'd — exactly the reference's DDP objective, where each
    GPU's ``NT_Xent`` sees only its local 2B embeddings and gradients are
    averaged by the all-reduce.
    """
    loss = ntxent_loss(z0, z1, temperature=temperature, reduction="mean")
    if axis_name is not None:
        loss = jax.lax.pmean(loss, axis_name)
    return loss
