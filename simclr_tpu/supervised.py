"""Fully-supervised baseline entry point.

TPU-native counterpart of ``/root/reference/supervised.py``: same SPMD shape
as pretraining but cross-entropy on :class:`SupervisedModel`, with a
distributed validation pass after every epoch — the reference's
``dist.barrier`` + ``dist.reduce`` sums (``supervised.py:137-139``) become a
``psum`` inside one jitted eval step. Keeps only the best checkpoint by
validation loss or accuracy, deleting the previous best
(``supervised.py:144-162``).

Improvement over the reference, by design (like main.py's):
``experiment.resume=true`` restores the persisted best checkpoint and
continues from its epoch — the reference restarts 200-epoch runs from
scratch on any failure (no checkpoint-load path, SURVEY §5.3).

    python -m simclr_tpu.supervised parameter.epochs=200
"""

from __future__ import annotations

import itertools
import math
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from simclr_tpu.config import Config, check_supervised_conf, load_config, resolve_save_dir
from simclr_tpu.data.cifar import NUM_CLASSES, load_dataset
from simclr_tpu.data.pipeline import EpochIterator, epoch_index_matrix
from simclr_tpu.data.prefetch import prefetch
from simclr_tpu.models.contrastive import SupervisedModel
from simclr_tpu.obs.anomaly import maybe_detector
from simclr_tpu.obs.compile import maybe_sentry
from simclr_tpu.obs.device import maybe_dump_oom_profile, maybe_monitor
from simclr_tpu.obs.events import EventLog
from simclr_tpu.obs.exporter import maybe_start_exporter
from simclr_tpu.obs.telemetry import Telemetry
from simclr_tpu.ops.lars import get_weight_decay_mask, lars
from simclr_tpu.parallel.compress import DEFAULT_COMM_CHUNKS, normalize_overlap
from simclr_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    enable_async_collective_flags,
    mesh_from_config,
    mesh_host_count,
    process_local_rows,
    put_global_batch,
    put_replicated,
    put_row_sharded,
    put_tree,
    replicated_sharding,
    validate_per_device_batch,
)
from simclr_tpu.parallel.steps import (
    check_epoch_compile_preconditions,
    make_supervised_epoch_fn,
    make_supervised_eval_step,
    make_supervised_step,
)
from simclr_tpu.parallel.train_state import create_train_state, param_count
from simclr_tpu.supervisor.guard import (
    PoisonedRun,
    PreemptedRun,
    RunGuard,
    preempt_checkpoint_name,
    resume_point,
)
from simclr_tpu.supervisor.topology import (
    check_resume_topology,
    read_topology,
    write_topology,
)
from simclr_tpu.utils.checkpoint import (
    CheckpointCorruptionError,
    checkpoint_name,
    delete_checkpoint,
    list_checkpoints,
    restore_checkpoint_with_fallback,
    save_checkpoint,
)
from simclr_tpu.utils.logging import get_logger, is_logging_host
from simclr_tpu.utils.profiling import StepTimer, StepTraceWindow
from simclr_tpu.utils.schedule import calculate_initial_lr, warmup_cosine_schedule

logger = get_logger()


def _compute_dtype(cfg: Config):
    name = str(cfg.select("precision.compute_dtype", "bfloat16"))
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[name]


def run_supervised(cfg: Config) -> dict:
    check_supervised_conf(cfg)
    if int(cfg.select("runtime.epochs_per_compile", 1) or 1) > 1:
        # superepochs fold the pretrain monitor into the compiled program;
        # the supervised loop validates/early-stops on host every epoch, so
        # a K-epoch program has no correct place to put that logic
        raise ValueError(
            "runtime.epochs_per_compile > 1 (superepochs) applies to "
            "contrastive pretraining only; supervised training validates "
            "every epoch on host — set runtime.epochs_per_compile=1"
        )
    seed = int(cfg.parameter.seed)

    comm_overlap = str(
        normalize_overlap(cfg.select("parallel.comm_overlap", "off"))
    )
    comm_chunks = int(cfg.select("parallel.comm_chunks", DEFAULT_COMM_CHUNKS))
    if comm_overlap == "async":
        # must land in XLA_FLAGS before mesh_from_config initializes the
        # backend; no-op off-TPU (parallel/mesh.py)
        enable_async_collective_flags()
    mesh = mesh_from_config(cfg)
    if mesh.shape.get(MODEL_AXIS, 1) > 1 and is_logging_host():
        logger.warning(
            "mesh.model=%d: the supervised baseline has no tensor-parallel "
            "path (the fc head is tiny); model-axis replicas duplicate work. "
            "Prefer mesh.model=1 here.", mesh.shape[MODEL_AXIS],
        )
    global_batch = validate_per_device_batch(int(cfg.experiment.batches), mesh)
    synthetic_ok = bool(cfg.select("experiment.synthetic_data", False))
    data_dir = cfg.select("experiment.data_dir")
    train_ds = load_dataset(
        cfg.experiment.name, "train", data_dir=data_dir, synthetic_ok=synthetic_ok,
        synthetic_size=cfg.select("experiment.synthetic_size"),
        synthetic_noise=cfg.select("experiment.synthetic_noise"),
    )
    val_ds = load_dataset(
        cfg.experiment.name, "test", data_dir=data_dir, synthetic_ok=synthetic_ok,
        synthetic_size=cfg.select("experiment.synthetic_size"),
        synthetic_noise=cfg.select("experiment.synthetic_noise"),
    )
    num_classes = NUM_CLASSES[cfg.experiment.name]

    steps_per_epoch = len(train_ds) // global_batch
    epochs = int(cfg.parameter.epochs)
    total_steps = epochs * steps_per_epoch
    warmup_steps = int(cfg.parameter.warmup_epochs) * steps_per_epoch

    # reference parity scales the base LR by the PER-DEVICE batch
    # (lr_utils.py:11-15); 'global' scales by the full mesh-wide batch (the
    # paper's large-batch LARS recipe, conf/experiment/cifar10-large-batch)
    lr_batch = (
        global_batch
        if str(cfg.select("parameter.lr_scale_batch", "per_device")) == "global"
        else int(cfg.experiment.batches)
    )
    lr0 = calculate_initial_lr(
        float(cfg.experiment.lr),
        lr_batch,
        bool(cfg.parameter.linear_schedule),
    )
    schedule = warmup_cosine_schedule(lr0, total_steps, warmup_steps)
    tx = lars(
        schedule,
        trust_coefficient=0.001,
        weight_decay=float(cfg.experiment.decay),
        weight_decay_mask=get_weight_decay_mask(
            str(cfg.select("optimizer.weight_decay_mask", "structural")),
            str(cfg.experiment.base_cnn),
        ),
        momentum=float(cfg.parameter.momentum),
    )

    model = SupervisedModel(
        base_cnn=cfg.experiment.base_cnn,
        num_classes=num_classes,
        cifar_stem=True,
        dtype=_compute_dtype(cfg),
        bn_cross_replica_axis=DATA_AXIS,
    )
    state = create_train_state(
        model, tx, jax.random.key(seed), jnp.zeros((2, 32, 32, 3), jnp.float32)
    )
    state = put_tree(state, replicated_sharding(mesh))

    save_dir = resolve_save_dir(cfg)
    # run telemetry + event timeline (simclr_tpu/obs/, docs/OBSERVABILITY.md),
    # constructed BEFORE the step builders so the compile sentry can watch
    # them. arch=None: the roofline FLOP model covers the pretrain step only,
    # so the supervised MFU gauge honestly reads 0.
    n_hosts = mesh_host_count(mesh)
    telemetry = Telemetry(
        arch=None,
        per_device_batch=int(cfg.experiment.batches),
        global_batch=global_batch,
        n_devices=jax.device_count(),
        mesh_hosts=n_hosts,
        grad_allreduce=str(cfg.select("parallel.grad_allreduce", "exact")),
        grad_elements=param_count(state.params),
        allreduce_devices=mesh.shape[DATA_AXIS],
        comm_overlap=comm_overlap,
        comm_chunks=comm_chunks,
    )
    events = EventLog(
        save_dir,
        enabled=bool(cfg.select("telemetry.events", True)) and is_logging_host(),
    )
    # fault-tolerance guard: preemption checkpointing, heartbeat, non-finite
    # loss rollback (simclr_tpu/supervisor/, docs/FAULT_TOLERANCE.md)
    guard = RunGuard(
        save_dir,
        nan_retry_budget=int(cfg.select("supervisor.nan_retry_budget", 2)),
        telemetry=telemetry,
        events=events,
        process_index=jax.process_index(),
    )
    # step anomaly detection (obs/anomaly.py): slow-step classifier + stall
    # watchdog + rate-limited auto-trace, host clock reads only
    detector = (
        maybe_detector(cfg, save_dir, telemetry=telemetry, events=events)
        if is_logging_host() else None
    )
    # compile sentry (obs/compile.py): times/fingerprints/cost-analyzes
    # every step compilation, alarms on post-warmup recompiles. Runs on
    # EVERY host so per-host compile counters feed the fleet view
    sentry = maybe_sentry(
        cfg, telemetry=telemetry, events=events, detector=detector
    )

    epoch_compile = bool(cfg.select("runtime.epoch_compile", False))
    eval_step = make_supervised_eval_step(model, mesh)
    data_shard = batch_sharding(mesh)
    # analytic per-chip resident dataset bytes from the epoch-compile
    # preflight; the DeviceMonitor reconciles it against measured live HBM
    resident_bytes = None
    if epoch_compile:
        # see main.py: sharded residency keeps N/n_data rows per data shard
        residency = str(cfg.select("runtime.dataset_residency", "replicated"))
        resident_bytes = check_epoch_compile_preconditions(
            len(train_ds), global_batch, cfg.select("experiment.profile_dir"),
            dataset_bytes=train_ds.images.nbytes + train_ds.labels.nbytes,
            n_data_shards=mesh.shape[DATA_AXIS],
            residency=residency,
        )
        epoch_fn = make_supervised_epoch_fn(
            model, tx, mesh, strength=float(cfg.experiment.strength),
            residency=residency,
            grad_allreduce=str(cfg.select("parallel.grad_allreduce", "exact")),
            comm_overlap=comm_overlap,
            comm_chunks=comm_chunks,
            augment_impl=str(cfg.select("runtime.augment_impl", "xla")),
            sentry=sentry,
        )
        put_dataset = put_replicated if residency == "replicated" else put_row_sharded
        images_all = put_dataset(train_ds.images, mesh)
        labels_all = put_dataset(train_ds.labels, mesh)
        train_iter = None
    else:
        train_step = make_supervised_step(
            model, tx, mesh, strength=float(cfg.experiment.strength),
            grad_allreduce=str(cfg.select("parallel.grad_allreduce", "exact")),
            comm_overlap=comm_overlap,
            comm_chunks=comm_chunks,
            augment_impl=str(cfg.select("runtime.augment_impl", "xla")),
            sentry=sentry,
        )
        train_iter = EpochIterator(
            train_ds, global_batch, seed=seed, shuffle=True, sharding=data_shard,
            gather_threads=int(cfg.parameter.num_workers),
        )
    # live HBM accounting (obs/device.py): sampled per scrape from the
    # exporter thread — host-side allocator queries, zero device syncs
    # every host monitors its own local devices' HBM for the fleet view
    monitor = maybe_monitor(
        cfg, events=events, expected_resident_bytes=resident_bytes
    )
    if monitor is not None:
        telemetry.attach_device_monitor(monitor)
    # validation: no shuffle, keep every sample (reference drop_last=False,
    # supervised.py:219-223). The tail remainder is zero-padded to the static
    # batch shape and masked out inside the one jitted eval step — a single
    # code path, same dtype/sharding as full batches, multi-host safe.
    val_steps = math.ceil(len(val_ds) / global_batch)
    val_pad = val_steps * global_batch - len(val_ds)
    val_images = val_ds.images
    val_labels = val_ds.labels
    val_valid = np.ones(len(val_ds), np.float32)
    if val_pad:
        val_images = np.concatenate(
            [val_images, np.zeros((val_pad, *val_images.shape[1:]), val_images.dtype)]
        )
        val_labels = np.concatenate([val_labels, np.zeros(val_pad, val_labels.dtype)])
        val_valid = np.concatenate([val_valid, np.zeros(val_pad, np.float32)])
    val_local = process_local_rows(global_batch)

    def run_validation(st) -> tuple[float, float]:
        """One full distributed validation sweep (reference
        supervised.py:30-58,135-139); the tail batch rides the same jitted
        step via the valid mask."""
        sum_loss, correct, count = 0.0, 0.0, 0.0
        for start in range(0, val_steps * global_batch, global_batch):
            sl = slice(start, start + global_batch)
            totals = eval_step(
                st.params,
                st.batch_stats,
                put_global_batch(val_images[sl][val_local], data_shard),
                put_global_batch(val_labels[sl][val_local], data_shard),
                put_global_batch(val_valid[sl][val_local], data_shard),
            )
            sum_loss += float(totals["sum_loss"])
            correct += float(totals["correct"])
            count += float(totals["count"])
        return sum_loss / max(count, 1.0), correct / max(count, 1.0)

    metric = str(cfg.parameter.metric)
    if is_logging_host():
        os.makedirs(save_dir, exist_ok=True)
        logger.info(
            "supervised %s: %d params, mesh %s, global batch %d, %d epochs, lr0 %.4f",
            cfg.experiment.name, param_count(state.params), dict(mesh.shape),
            global_batch, epochs, lr0,
        )

    base_key = jax.random.key(seed + 1)
    best_value = None
    best_path = None
    best_epoch = 0
    start_epoch = 1
    skip_steps = 0
    events.emit(
        "run_start", entry="supervised", epochs=epochs,
        steps_per_epoch=steps_per_epoch, global_batch=global_batch,
        pid=os.getpid(),
    )
    # Resume (VERDICT r3 item 6) — the same restore→start_epoch mechanism as
    # main.py, adapted to the best-only deletion policy: normally the only
    # checkpoint on disk IS the previous best, so training rewinds to the
    # best epoch; a "-preempt" checkpoint (newer) wins when present. The
    # fallback restore happens BEFORE any stale-checkpoint cleanup — a
    # corrupt newest must be able to fall back to the older one, so deleting
    # first would destroy the very candidates the fallback needs. One
    # re-validation of the restored state re-establishes best_value/best_path
    # so the first post-resume epoch can't spuriously "improve" over None and
    # delete the checkpoint it just resumed from.
    if bool(cfg.select("experiment.resume", False)):
        # the prior generation's topology record, read before this run
        # overwrites the sidecar below (elastic remesh accept/reject)
        prior_topology = read_topology(save_dir)
        t_restore = time.perf_counter()
        restored, ckpt = restore_checkpoint_with_fallback(save_dir, state)
        if restored is not None:
            telemetry.observe_restore(time.perf_counter() - t_restore)
            state = restored
            # best-only invariant restored AFTER the successful restore:
            # drop everything except what we actually resumed from (stale
            # best from a crash window, preempt checkpoints, corrupt newest)
            for stale in list_checkpoints(save_dir):
                if os.path.abspath(stale) != os.path.abspath(ckpt):
                    delete_checkpoint(stale)
            start_epoch, skip_steps = resume_point(
                int(state.step), steps_per_epoch
            )
            # cross-topology resume (elastic remesh): global batch must be
            # preserved and the checkpoint must sit on an epoch boundary —
            # same contract as main.py
            topology_change = check_resume_topology(
                prior_topology,
                n_devices=jax.device_count(),
                n_processes=n_hosts,
                global_batch=global_batch,
                skip_steps=skip_steps,
            )
            if topology_change is not None:
                events.emit("topology_change", **topology_change)
                logger.info(
                    "Cross-topology resume: %d -> %d devices "
                    "(%d -> %d hosts), per-device batch now %d",
                    topology_change["devices_before"],
                    topology_change["devices_after"],
                    topology_change["hosts_before"],
                    topology_change["hosts_after"],
                    topology_change["per_device_batch"],
                )
            val_loss, val_acc = run_validation(state)
            telemetry.observe_val_acc(val_acc)
            best_value = val_loss if metric == "loss" else val_acc
            best_path = ckpt
            best_epoch = start_epoch - 1
            # the resumed epochs re-run: re-seat the timeline so their
            # epoch/checkpoint events are not duplicated
            events.reseat(start_epoch)
            events.emit(
                "resume", epoch=start_epoch, step=int(state.step),
                skip_steps=skip_steps, checkpoint=ckpt,
            )
            if is_logging_host():
                logger.info(
                    "Resumed from %s at epoch %d (best %s=%.4f re-validated)",
                    ckpt, start_epoch, metric, best_value,
                )
    if is_logging_host():
        write_topology(
            save_dir,
            n_devices=jax.device_count(),
            n_processes=n_hosts,
            global_batch=global_batch,
        )
    if epoch_compile and skip_steps:
        raise ValueError(
            f"checkpoint at step {int(state.step)} is mid-epoch "
            f"({skip_steps}/{steps_per_epoch} steps into epoch {start_epoch}) "
            "and cannot resume under runtime.epoch_compile=true; resume with "
            "runtime.epoch_compile=false"
        )
    history = []
    t_start = time.time()
    # host-side mirror of state.step: avoids per-step device sync
    cur_step = (start_epoch - 1) * steps_per_epoch + skip_steps
    # steady-state training throughput like main.py's: validation sweeps and
    # checkpoint I/O are pause()d out of the timed window. In epoch_compile
    # mode one tick covers a whole epoch of steps.
    timer = StepTimer(
        global_batch * (steps_per_epoch if epoch_compile else 1),
        warmup=1 if epoch_compile else 3,
    )
    tracer = StepTraceWindow(
        cfg.select("experiment.profile_dir"),
        start=cur_step + 2,
        length=int(cfg.select("experiment.profile_steps", 10) or 10),
        enabled=is_logging_host(),
    )
    # bound before the loop: a resume whose start_epoch exceeds epochs (the
    # run already completed) must still reach tracer.close/timer.summary
    train_metrics = {"loss": jnp.zeros(()), "accuracy": jnp.zeros(())}
    stem = f"supervised-{cfg.experiment.name}.pt"
    # per-host /metrics + /healthz + /debug/trace exporter (disabled by
    # default — see telemetry.port in conf/supervised_config.yaml); process
    # i>0 publishes telemetry.p<i>.ready for the FleetCollector
    exporter = maybe_start_exporter(
        cfg, telemetry, save_dir, process_index=jax.process_index()
    )
    guard.install_signals()
    try:
        epoch = start_epoch
        while epoch <= epochs:
            epoch_start_step = cur_step
            epoch_t0 = time.perf_counter()
            if epoch_compile:
                idx_e = jnp.asarray(
                    epoch_index_matrix(
                        len(train_ds), seed, epoch, steps_per_epoch, global_batch
                    )
                )
                state, epoch_metrics = epoch_fn(
                    state, images_all, labels_all, idx_e, base_key, cur_step
                )
                train_metrics = {k: v[-1] for k, v in epoch_metrics.items()}
                timer.tick(epoch_metrics["loss"])
                cur_step += steps_per_epoch
                if detector is not None:
                    # one tick per epoch: the loop's unit of progress here
                    detector.tick(cur_step, epoch)
            else:
                batches = train_iter.batches(epoch)
                if skip_steps:
                    # mid-epoch resume: replay the epoch's deterministic batch
                    # order past the consumed prefix (step RNG folds on the
                    # absolute cur_step, so the continuation is exact)
                    batches = itertools.islice(batches, skip_steps, None)
                    skip_steps = 0
                for batch in prefetch(batches):
                    tracer.tick(cur_step, pending=train_metrics["loss"])
                    step_rng = jax.random.fold_in(base_key, cur_step)
                    state, train_metrics = train_step(
                        state, batch["image"], batch["label"], step_rng
                    )
                    timer.tick(train_metrics["loss"])
                    cur_step += 1
                    if detector is not None:
                        # BEFORE the beat: the beat is where fault injection
                        # wedges, and the watchdog must already be armed
                        detector.tick(cur_step, epoch)
                    guard.beat(cur_step, epoch)
                    if guard.preempt_requested:
                        break
            if detector is not None:
                # validation/checkpoint work at the boundary is not a step:
                # disarm so it can never read as a stall
                detector.pause()
            if guard.preempt_requested:
                # land a resumable checkpoint (alongside the untouched best),
                # then exit 75 via main(); resume restores this newest state
                # and re-establishes the best-only invariant
                timer.pause(train_metrics["loss"])
                path = os.path.join(
                    save_dir,
                    preempt_checkpoint_name(cur_step, steps_per_epoch, stem),
                )
                t_save = time.perf_counter()
                save_checkpoint(path, state)
                telemetry.observe_save(time.perf_counter() - t_save)
                events.emit("preempt", step=cur_step, epoch=epoch, checkpoint=path)
                guard.beat_preempted(cur_step, epoch)
                raise PreemptedRun(path)

            epoch_loss = guard.checked_loss(
                cur_step, float(train_metrics["loss"])
            )
            # telemetry BEFORE the beat so the heartbeat snapshot is fresh;
            # host floats only (see obs/telemetry.py) — zero extra syncs,
            # and every host updates its OWN gauges for the fleet view
            telemetry.observe_epoch(
                epoch, epochs=epochs, step=cur_step,
                steps=cur_step - epoch_start_step,
                seconds=time.perf_counter() - epoch_t0,
                loss=epoch_loss,
                lr=float(schedule(max(cur_step - 1, 0))),
            )
            guard.beat(cur_step, epoch, loss=epoch_loss)
            if not math.isfinite(epoch_loss):
                # roll back to the newest verified checkpoint; a different
                # RNG stream on the retry (see main.py)
                try:
                    t_restore = time.perf_counter()
                    rolled, rpath = restore_checkpoint_with_fallback(
                        save_dir, state
                    )
                except CheckpointCorruptionError as e:
                    raise PoisonedRun(str(e)) from e
                guard.record_rollback(epoch_loss, rpath)
                telemetry.observe_restore(time.perf_counter() - t_restore)
                state = rolled
                cur_step = int(state.step)
                epoch, skip_steps = resume_point(cur_step, steps_per_epoch)
                history = [h for h in history if h["epoch"] < epoch]
                # the rolled-back epochs re-run: re-seat the timeline too
                events.reseat(epoch)
                val_loss, val_acc = run_validation(state)
                telemetry.observe_val_acc(val_acc)
                best_value = val_loss if metric == "loss" else val_acc
                best_path = rpath
                best_epoch = epoch - 1
                base_key = jax.random.fold_in(
                    jax.random.key(seed + 1), guard.nan_rollbacks
                )
                continue

            timer.pause(train_metrics["loss"])  # keep eval out of the imgs/sec window
            val_loss, val_acc = run_validation(state)
            telemetry.observe_val_acc(val_acc)
            history.append({"epoch": epoch, "val_loss": val_loss, "val_acc": val_acc})
            events.emit(
                "epoch", epoch=epoch, step=cur_step, loss=epoch_loss,
                val_loss=val_loss, val_acc=val_acc,
                seconds=round(time.perf_counter() - epoch_t0, 6),
            )
            if is_logging_host():
                imgs_per_sec = (
                    (cur_step - (start_epoch - 1) * steps_per_epoch)
                    * global_batch / max(time.time() - t_start, 1e-9)
                )
                logger.info(
                    "Epoch:%d/%d progress:%.3f train_loss:%.3f val_loss:%.4f "
                    "val_acc:%.4f lr:%.7f imgs/sec(cum):%.0f",
                    epoch, epochs, epoch / epochs, epoch_loss,
                    val_loss, val_acc, float(schedule(max(cur_step - 1, 0))),
                    imgs_per_sec,
                )

            # best-only checkpoint policy (reference supervised.py:144-162)
            value = val_loss if metric == "loss" else val_acc
            improved = best_value is None or (
                value < best_value if metric == "loss" else value > best_value
            )
            if improved:
                # save the NEW best before deleting the old one: a crash between
                # the two must leave at least one resumable checkpoint on disk
                # (orbax writes are atomic; epoch-numbered names never collide)
                prev_best = best_path
                best_value = value
                best_epoch = epoch
                best_path = os.path.join(save_dir, checkpoint_name(epoch, stem))
                t_save = time.perf_counter()
                save_checkpoint(best_path, state)
                telemetry.observe_save(time.perf_counter() - t_save)
                events.emit("checkpoint", epoch=epoch, path=best_path)
                guard.after_save(epoch, best_path)
                if prev_best is not None:
                    delete_checkpoint(prev_best)
            timer.resume()
            epoch += 1
    except Exception as exc:
        # allocator RESOURCE_EXHAUSTED: capture the device memory profile +
        # an ``oom`` event before the error propagates (no-op otherwise)
        if is_logging_host():
            maybe_dump_oom_profile(save_dir, exc, events=events)
        raise
    finally:
        guard.restore_signals()
        if detector is not None:
            detector.close()
        if exporter is not None:
            exporter.close()

    tracer.close(pending=train_metrics["loss"])
    throughput = timer.summary()
    if is_logging_host() and throughput["steps"] > 0:
        timed_steps = throughput["steps"] * (steps_per_epoch if epoch_compile else 1)
        logger.info(
            "steady-state: %.0f imgs/sec (%.0f per chip) over %d steps",
            throughput["imgs_per_sec"], throughput["imgs_per_sec_per_chip"],
            timed_steps,
        )
    summary = {
        "imgs_per_sec_steady": throughput["imgs_per_sec"],
        "best_epoch": best_epoch,
        "best_value": best_value,
        "best_path": best_path,
        "metric": metric,
        "history": history,
        "save_dir": save_dir,
        "steps": int(state.step),
    }
    if is_logging_host():
        import json

        from simclr_tpu.utils.ioutil import atomic_write

        atomic_write(
            os.path.join(save_dir, "supervised_results.json"),
            lambda f: json.dump(summary, f, indent=1),
        )
    events.emit(
        "run_end", step=int(state.step), best_epoch=best_epoch, metric=metric,
    )
    return summary


def main(argv: list[str] | None = None):
    from simclr_tpu.parallel.multihost import maybe_initialize_multihost
    from simclr_tpu.utils.platform import ensure_platform

    ensure_platform()
    maybe_initialize_multihost()
    from simclr_tpu.config import run_multirun, split_multirun_flag
    from simclr_tpu.supervisor.guard import EXIT_POISONED, EXIT_PREEMPTED

    multirun, args = split_multirun_flag(list(sys.argv[1:] if argv is None else argv))
    # exit-code contract (docs/FAULT_TOLERANCE.md): 75 = preempted but
    # resumable, 76 = poisoned (restarting cannot help)
    try:
        if multirun:
            return run_multirun(run_supervised, "supervised_config", args)
        cfg = load_config("supervised_config", overrides=args)
        return run_supervised(cfg)
    except PreemptedRun as e:
        logger.info("%s", e)
        sys.exit(EXIT_PREEMPTED)
    except PoisonedRun as e:
        logger.error("%s", e)
        sys.exit(EXIT_POISONED)


if __name__ == "__main__":
    main()
