"""Downstream evaluation of frozen features (centroid / linear / nonlinear).

TPU-native counterpart of ``/root/reference/eval.py``: for every checkpoint
in ``experiment.target_dir``, extract frozen features of the clean (no-aug)
train/val sets, then score a probe:

  * ``centroid``  — per-class feature means, top-1/top-k accuracy
    (``eval.py:61-85``, ``model.py:24-53``);
  * ``linear`` / ``nonlinear`` — probe trained with SGD(nesterov) + cosine
    over all steps, recording per-epoch train/val accuracy+loss exactly like
    ``learnable_eval`` (``eval.py:88-190``); the reference's
    ``NonLinearClassifier`` import is a latent defect (SURVEY §2.5.1) — the
    class is reconstructed in ``models/heads.py``.

All results land in one JSON blob (``eval.py:322-325``). Improvement over
the reference, by design: the blob is persisted after EVERY checkpoint and
``experiment.resume=true`` skips checkpoints already present, so a crashed
multi-checkpoint sweep resumes instead of redoing hours of probe training.

    python -m simclr_tpu.eval parameter.classifier=linear \
        experiment.target_dir=results/cifar10/seed-7/...

Probe training runs as one jitted step over the device mesh with the cached
feature matrix resident on device — the feature extraction is the only
model-sized compute, matching the reference's structure.
"""

from __future__ import annotations

import functools
import json
import math
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

from simclr_tpu.config import Config, check_eval_conf, load_config, resolve_save_dir
from simclr_tpu.data.augment import to_float
from simclr_tpu.data.cifar import NUM_CLASSES, load_dataset
from simclr_tpu.models.contrastive import ContrastiveModel
from simclr_tpu.models.heads import (
    LinearClassifier,
    NonLinearClassifier,
    centroid_logits,
    centroid_weights,
)
from simclr_tpu.parallel.mesh import (
    batch_sharding,
    mesh_from_config,
    process_local_rows,
    put_global_batch,
    validate_per_device_batch,
)
from simclr_tpu.parallel.steps import make_encode_step
from simclr_tpu.utils.checkpoint import list_checkpoints_or_raise, restore_checkpoint
from simclr_tpu.utils.fetch import fetch
from simclr_tpu.utils.ioutil import atomic_write
from simclr_tpu.utils.logging import get_logger, is_logging_host
from simclr_tpu.utils.schedule import calculate_initial_lr

logger = get_logger()


def build_eval_model(cfg: Config) -> ContrastiveModel:
    """The frozen-feature extraction model, shared by eval, save_features,
    and main's ``eval_every`` monitor so the three surfaces produce
    numerically identical features for one checkpoint.

    Explicit ``dtype=float32``: the TRAINING model computes in bfloat16 by
    default, but extraction mirrors the reference's float32 torch forward
    (``/root/reference/eval.py:31-58``) — probes see full-precision
    features.
    """
    return ContrastiveModel(
        base_cnn=cfg.experiment.base_cnn, d=int(cfg.parameter.d),
        cifar_stem=True, dtype=jnp.float32,
    )


def load_model_variables(ckpt_path: str) -> dict:
    """Pull {params, batch_stats} out of a saved TrainState checkpoint.

    The analogue of the reference's ``module.``-prefix strip + partial
    ``load_state_dict`` (``eval.py:256-263``): our checkpoints carry the
    whole train state; eval consumes only the model variables.
    """
    raw = restore_checkpoint(ckpt_path, None)
    # materialize to host numpy: orbax restores arrays WITH their saved
    # shardings, and a checkpoint written on a different mesh layout (e.g. a
    # tensor-parallel (data, model) run) would otherwise be rejected by this
    # process's jit shardings
    return jax.tree.map(
        np.asarray,
        {"params": raw["params"], "batch_stats": raw.get("batch_stats", {})},
    )


def extract_features(
    model, variables, images: np.ndarray, mesh, batch: int, use_full_encoder: bool
) -> np.ndarray:
    """Frozen features of a full split, tail-padded to static batch shapes."""
    encode = make_encode_step(model, mesh, use_full_encoder=use_full_encoder)
    sharding = batch_sharding(mesh)
    n = len(images)
    steps = math.ceil(n / batch)
    pad = steps * batch - n
    if pad:
        images = np.concatenate([images, np.zeros((pad, *images.shape[1:]), images.dtype)])
    local = process_local_rows(batch)  # every host holds the full split;
    # upload only this process's row block of each chunk (multi-host safe)
    outs = []
    for i in range(steps):
        chunk = put_global_batch(images[i * batch : (i + 1) * batch][local], sharding)
        # dispatch only — async dispatch pipelines upload/compute across
        # chunks; the device->host sync happens once below
        outs.append(encode(variables["params"], variables["batch_stats"], chunk))
    return np.concatenate([fetch(o) for o in outs])[:n]


def _topk_correct(logits: jnp.ndarray, labels: jnp.ndarray, top_k: int):
    """(top-1 corrects, top-k corrects) as scalars."""
    _, pred = jax.lax.top_k(logits, top_k)
    top1 = jnp.sum(pred[:, 0] == labels)
    topk = jnp.sum(jnp.any(pred == labels[:, None], axis=1))
    return top1, topk


def centroid_probe(
    train_X, train_y, val_X, val_y, num_classes: int, top_k: int
) -> dict:
    """Reference centroid evaluation (``eval.py:279-293``, ``model.py:24-53``)."""
    weights = centroid_weights(jnp.asarray(train_X), jnp.asarray(train_y), num_classes)

    @jax.jit
    def score(X, y):
        return _topk_correct(centroid_logits(X, weights), y, top_k)

    tr1, trk = score(jnp.asarray(train_X), jnp.asarray(train_y))
    va1, vak = score(jnp.asarray(val_X), jnp.asarray(val_y))
    return {
        "train_acc": float(tr1) / len(train_y),
        f"train_top_{top_k}_acc": float(trk) / len(train_y),
        "val_acc": float(va1) / len(val_y),
        f"val_top_{top_k}_acc": float(vak) / len(val_y),
    }


def kmeans(
    features, num_clusters: int, *, iters: int = 8, seed: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """Lloyd's k-means over feature rows, built from the centroid probe's
    primitives — the IVF coarse quantizer for the serve tier's ANN path
    (``serve.ann_cells``, ``serve/retrieval.py``).

    Assignment uses the same ``X @ W`` product as :func:`centroid_logits`
    corrected to true nearest-centroid (``argmax(x·c − ½‖c‖²)``, equivalent
    to min squared distance); the update is exactly
    :func:`centroid_weights` — per-cluster means — with empty clusters
    RETAINING their previous centroid (``centroid_weights`` clips empty
    counts to 1 and yields zeros, which would teleport the centroid to the
    origin and strand it). Init is a seeded permutation of distinct rows, so
    the clustering — and therefore the serve tier's cell layout — is
    deterministic per (corpus, seed). Returns ``(centroids (C, d) f32,
    assignments (n,) int32)`` as host numpy.
    """
    X = jnp.asarray(np.asarray(features, np.float32))
    n, _ = X.shape
    c = max(1, min(int(num_clusters), n))
    init = np.random.default_rng(seed).permutation(n)[:c]
    weights = X[jnp.asarray(init)].T  # (d, C), the centroid_weights layout

    @jax.jit
    def step(w):
        logits = centroid_logits(X, w) - 0.5 * jnp.sum(w * w, axis=0)
        assign = jnp.argmax(logits, axis=1)
        counts = jnp.sum(jax.nn.one_hot(assign, c, dtype=X.dtype), axis=0)
        w2 = centroid_weights(X, assign, c)
        return jnp.where(counts[None, :] > 0, w2, w), assign

    assign = None
    for _ in range(max(int(iters), 1)):
        weights, assign = step(weights)
    return (
        np.asarray(weights.T, np.float32),
        np.asarray(assign, np.int32),
    )


def make_local_centroid_monitor(
    model,
    *,
    num_classes: int,
    n_train: int,
    n_test: int,
    top_k: int = 5,
    chunk: int = 512,
    data_axis: str = None,
):
    """The centroid monitor as a PURE jittable per-shard function — the
    device-resident counterpart of :func:`extract_features` +
    :func:`centroid_probe`, built to run INSIDE a ``shard_map`` over the data
    axis (the superepoch scan, ``parallel/steps.py``) so ``eval_every``
    monitoring costs zero host syncs.

    Contract of the returned callable (all inputs device-resident)::

        local_monitor(params, batch_stats, train_rows, train_labels,
                      test_rows, test_labels) -> {metric: scalar}

    where ``train_rows``/``test_rows`` are this shard's CONTIGUOUS row block
    of the (tail-padded) split — shard ``k`` holds global rows
    ``[k*R, (k+1)*R)``, the ``mesh.put_row_sharded`` layout — and
    ``train_labels``/``test_labels`` are the full replicated label vectors
    padded to ``n_shards * R``. Padding rows are excluded by position
    (``k*R + i >= n``), so the label padding value is irrelevant.

    Numerics mirror the host path exactly by construction: the same f32
    ``build_eval_model`` encode forward (``train=False``, running BN stats),
    per-class mean centroids (``centroid_weights``), ``features @ centroids``
    logits (``centroid_logits``), and top-1/top-k corrects (``_topk_correct``)
    — except features never leave the device and the per-class sums/corrects
    are assembled with ``psum`` over the data axis instead of a host
    concatenate. Correct counts are integer sums, so the accuracies agree
    with the host path up to feature-level float drift flipping an argmax
    tie (test-asserted in tests/test_superepoch.py).

    The forward is chunked with an inner ``lax.scan`` (``chunk`` rows per
    iteration) to bound activation memory; the returned callable exposes
    ``metric_names`` so callers can build a structurally-identical skip
    branch for the ``eval_every`` gating ``lax.cond``.
    """
    if data_axis is None:
        from simclr_tpu.parallel.mesh import DATA_AXIS

        data_axis = DATA_AXIS

    def _features(params, batch_stats, rows):
        rows_local = rows.shape[0]
        c = min(chunk, rows_local)
        n_chunks = -(-rows_local // c)
        pad = n_chunks * c - rows_local
        if pad:
            rows = jnp.concatenate(
                [rows, jnp.zeros((pad, *rows.shape[1:]), rows.dtype)]
            )
        chunks = rows.reshape(n_chunks, c, *rows.shape[1:])

        def body(carry, xb):
            f = model.apply(
                {"params": params, "batch_stats": batch_stats},
                to_float(xb), train=False, method=model.encode,
            ).astype(jnp.float32)
            return carry, f

        _, feats = jax.lax.scan(body, None, chunks)
        return feats.reshape(n_chunks * c, -1)[:rows_local]

    def _split(params, batch_stats, rows, labels_all, n):
        rows_local = rows.shape[0]
        shard = jax.lax.axis_index(data_axis)
        feats = _features(params, batch_stats, rows)
        labels = jax.lax.dynamic_slice_in_dim(
            labels_all, shard * rows_local, rows_local
        )
        valid = (jnp.arange(rows_local) + shard * rows_local) < n
        return feats, labels, valid

    def _corrects(feats, labels, valid, weights):
        logits = centroid_logits(feats, weights)
        _, pred = jax.lax.top_k(logits, top_k)
        top1 = jnp.sum((pred[:, 0] == labels) & valid)
        topk = jnp.sum(jnp.any(pred == labels[:, None], axis=1) & valid)
        return (
            jax.lax.psum(top1, data_axis).astype(jnp.float32),
            jax.lax.psum(topk, data_axis).astype(jnp.float32),
        )

    def local_monitor(
        params, batch_stats, train_rows, train_labels, test_rows, test_labels
    ):
        tr_f, tr_y, tr_v = _split(
            params, batch_stats, train_rows, train_labels, n_train
        )
        # per-class mean centroids (centroid_weights semantics), assembled
        # from per-shard partial sums: one psum of a (d, C) matrix + (C,)
        one_hot = (
            jax.nn.one_hot(tr_y, num_classes, dtype=jnp.float32)
            * tr_v[:, None].astype(jnp.float32)
        )
        sums = jax.lax.psum(tr_f.T @ one_hot, data_axis)
        counts = jax.lax.psum(one_hot.sum(axis=0), data_axis)
        weights = sums / jnp.clip(counts, 1.0, None)

        tr1, trk = _corrects(tr_f, tr_y, tr_v, weights)
        te_f, te_y, te_v = _split(
            params, batch_stats, test_rows, test_labels, n_test
        )
        va1, vak = _corrects(te_f, te_y, te_v, weights)
        return {
            "train_acc": tr1 / n_train,
            f"train_top_{top_k}_acc": trk / n_train,
            "val_acc": va1 / n_test,
            f"val_top_{top_k}_acc": vak / n_test,
        }

    local_monitor.metric_names = (
        "train_acc", f"train_top_{top_k}_acc", "val_acc", f"val_top_{top_k}_acc",
    )
    return local_monitor


@functools.lru_cache(maxsize=8)
def _probe_program(
    kind: str,
    num_classes: int,
    n: int,
    batch: int,
    top_k: int,
    lr0: float,
    decay: float,
    momentum: float,
    total_steps: int,
    mesh=None,
):
    """(classifier, optimizer, jitted scan-of-scans probe program).

    Cached on the static probe configuration so evaluating N checkpoints of
    one run compiles the (large) probe program ONCE and reuses the
    executable — a fresh ``@jax.jit`` closure per checkpoint would re-trace
    and re-compile every time.

    With ``mesh`` (hashable) the per-epoch full-dataset metric sweeps — the
    probe run's dominant FLOPs, two dataset-sized matmuls per epoch — are
    sharded over the data axis via sharding constraints (GSPMD splits the
    matmul and psums the scalar sums back), instead of every device
    repeating identical work. The tiny sequential SGD steps stay replicated:
    they gather arbitrary shuffled rows and wouldn't amortize collectives.
    """
    steps_per_epoch = math.ceil(n / batch)
    schedule = optax.cosine_decay_schedule(lr0, decay_steps=total_steps)
    tx = optax.chain(
        optax.add_decayed_weights(decay),
        optax.trace(decay=momentum, nesterov=True),
        optax.scale_by_learning_rate(schedule),
    )
    if kind == "linear":
        clf = LinearClassifier(num_classes=num_classes)
    else:
        clf = NonLinearClassifier(num_classes=num_classes)
    has_bn = kind != "linear"
    bn_eps = 1e-5
    bn_momentum = 0.9  # torch BatchNorm1d momentum 0.1 == keep 0.9

    pad = steps_per_epoch * batch - n
    mask_np = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    mask_epoch = mask_np.reshape(steps_per_epoch, batch)

    # The nonlinear probe's BN runs FUNCTIONALLY on the NonLinearClassifier
    # param/stat trees rather than through flax's BatchNorm, for exact
    # reference semantics under the static-shape scan (probe-dynamics
    # parity, tests/test_probe_dynamics.py): the reference's drop_last=False
    # tail batch is SMALLER, so its BN statistics span only the real rows —
    # here the padded rows must be masked out of the batch mean/var — and
    # torch's running_var update uses the UNBIASED batch variance
    # (flax's uses the biased one).
    def _mlp_train_forward(p, stats, xb, mask):
        y = xb @ p["linear1"]["kernel"] + p["linear1"]["bias"]
        m = mask[:, None]
        n_real = jnp.maximum(mask.sum(), 1.0)
        mean = (y * m).sum(axis=0) / n_real
        var = (jnp.square(y - mean) * m).sum(axis=0) / n_real
        yn = (y - mean) * jax.lax.rsqrt(var + bn_eps)
        yn = yn * p["bn1"]["scale"] + p["bn1"]["bias"]
        unbiased = var * n_real / jnp.maximum(n_real - 1.0, 1.0)
        new_stats = {
            "bn1": {
                "mean": bn_momentum * stats["bn1"]["mean"] + (1 - bn_momentum) * mean,
                "var": bn_momentum * stats["bn1"]["var"]
                + (1 - bn_momentum) * unbiased,
            }
        }
        logits = jax.nn.relu(yn) @ p["linear2"]["kernel"] + p["linear2"]["bias"]
        return logits, new_stats

    def _mlp_eval_forward(p, stats, X):
        y = X @ p["linear1"]["kernel"] + p["linear1"]["bias"]
        yn = (y - stats["bn1"]["mean"]) * jax.lax.rsqrt(stats["bn1"]["var"] + bn_eps)
        yn = yn * p["bn1"]["scale"] + p["bn1"]["bias"]
        return jax.nn.relu(yn) @ p["linear2"]["kernel"] + p["linear2"]["bias"]

    def train_step(params, opt_state, batch_stats, xb, yb, mask):
        def loss_fn(p):
            if has_bn:
                logits, new_stats = _mlp_train_forward(p, batch_stats, xb, mask)
            else:
                logits = clf.apply({"params": p}, xb)
                new_stats = batch_stats
            per = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), yb
            )
            loss = (per * mask).sum() / jnp.maximum(mask.sum(), 1.0)
            return loss, new_stats

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, new_stats, loss

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from simclr_tpu.parallel.mesh import DATA_AXIS

        _rows = NamedSharding(mesh, P(DATA_AXIS))
        _rep = NamedSharding(mesh, P())

    def dataset_metrics(params, batch_stats, Xs, ys):
        if mesh is not None:
            Xs = jax.lax.with_sharding_constraint(Xs, _rows)
            ys = jax.lax.with_sharding_constraint(ys, _rows)
        if has_bn:
            logits = _mlp_eval_forward(params, batch_stats, Xs)
        else:
            logits = clf.apply({"params": params}, Xs)
        logits = logits.astype(jnp.float32)
        loss_sum = optax.softmax_cross_entropy_with_integer_labels(logits, ys).sum()
        top1, topk = _topk_correct(logits, ys, top_k)
        return top1.astype(jnp.float32), topk.astype(jnp.float32), loss_sum

    @jax.jit
    def run_probe(params, opt_state, batch_stats, idx_all, X, y, Xsw, ysw, Xv, yv):
        # features enter as jit ARGUMENTS, not closure constants, so they
        # are neither baked into the program nor duplicated per checkpoint.
        # The train matrix enters TWICE on purpose (X/y for the SGD path,
        # Xsw/ysw for the sweeps): GSPMD propagates dataset_metrics' row
        # constraint backward to whichever loop-invariant input the sweep
        # reads, and if the SGD path shared that input, every sequential
        # step's batch gather would compile into a cross-device gather +
        # all-reduce (observed in HLO). Distinct arguments give each use
        # its own sharding; the sharded duplicate costs 1/n_devices extra
        # memory per device.
        if mesh is not None:
            X = jax.lax.with_sharding_constraint(X, _rep)
            y = jax.lax.with_sharding_constraint(y, _rep)

        def step_body(carry, st):
            p, o, s = carry
            i, mk = st
            p, o, s, loss = train_step(p, o, s, X[i], y[i], mk)
            return (p, o, s), loss * mk.sum()

        def epoch_body(carry, idx_e):
            carry, losses = jax.lax.scan(
                step_body, carry, (idx_e, jnp.asarray(mask_epoch))
            )
            p, o, s = carry
            tr = dataset_metrics(p, s, Xsw, ysw)
            va = dataset_metrics(p, s, Xv, yv)
            return carry, (losses.sum(), tr, va)

        return jax.lax.scan(epoch_body, (params, opt_state, batch_stats), idx_all)

    return clf, tx, run_probe


def learnable_probe(
    cfg: Config,
    kind: str,
    train_X: np.ndarray,
    train_y: np.ndarray,
    val_X: np.ndarray,
    val_y: np.ndarray,
    num_classes: int,
    top_k: int,
    mesh=None,
) -> dict:
    """Train a linear/nonlinear probe, reference-exact recipe.

    SGD(nesterov=True, momentum, weight_decay=experiment.decay), initial LR
    ``calculate_initial_lr`` of the probe config, cosine over ALL steps with
    ``ceil`` step accounting (probe loaders have drop_last=False), scheduler
    stepped per batch (``/root/reference/eval.py:145-159``); per-epoch full
    train/val accuracy+loss sweeps (``eval.py:161-189``).

    TPU-native structure: the ENTIRE probe run — every epoch, every SGD step,
    every per-epoch metrics sweep — is one ``lax.scan``-of-``lax.scan`` XLA
    program dispatched once, with the cached feature matrix resident on
    device and per-epoch shuffles precomputed on host as an index tensor.
    The reference's eager loop pays a host round-trip per 512-row batch;
    here the per-epoch log lines are emitted after the compiled run.
    """
    epochs = int(cfg.parameter.epochs)
    batch = int(cfg.experiment.batches)
    seed = int(cfg.parameter.seed)
    n = len(train_X)
    steps_per_epoch = math.ceil(n / batch)
    total_steps = epochs * steps_per_epoch

    lr0 = calculate_initial_lr(
        float(cfg.experiment.lr), batch, bool(cfg.parameter.linear_schedule)
    )
    clf, tx, run_probe = _probe_program(
        kind,
        num_classes,
        n,
        batch,
        top_k,
        lr0,
        float(cfg.experiment.decay),
        float(cfg.parameter.momentum),
        max(total_steps, 1),
        mesh,
    )
    variables = clf.init(jax.random.key(seed), jnp.zeros((2, train_X.shape[1])))
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    opt_state = tx.init(params)

    X = jnp.asarray(train_X)
    y = jnp.asarray(train_y)
    Xv = jnp.asarray(val_X)
    yv = jnp.asarray(val_y)

    # per-epoch shuffles precomputed as one (epochs, steps, batch) tensor;
    # same RNG draw order as an eager per-epoch loop
    rng = np.random.default_rng(seed)
    pad = steps_per_epoch * batch - n
    idx_np = np.zeros((epochs, steps_per_epoch * batch), np.int32)
    for e in range(epochs):
        order = rng.permutation(n).astype(np.int32)
        idx_np[e, :n] = order
    idx_all = jnp.asarray(idx_np.reshape(epochs, steps_per_epoch, batch))

    (params, opt_state, batch_stats), (epoch_losses, tr_hist, va_hist) = run_probe(
        params, opt_state, batch_stats, idx_all, X, y, X, y, Xv, yv
    )
    epoch_losses = np.asarray(epoch_losses)
    tr1, trk, trl = (np.asarray(a) for a in tr_hist)
    va1, vak, val_ = (np.asarray(a) for a in va_hist)
    # .tolist() -> Python floats (JSON-serializable, like the eager loop's)
    train_accs = (tr1 / n).tolist()
    train_topk_accs = (trk / n).tolist()
    train_losses = (trl / n).tolist()
    val_accs = (va1 / len(val_y)).tolist()
    val_topk_accs = (vak / len(val_y)).tolist()
    val_losses = (val_ / len(val_y)).tolist()
    if is_logging_host():
        for epoch in range(1, epochs + 1):
            logger.info(
                "probe %s epoch:%d/%d loss:%.4f val_acc:%.4f",
                kind, epoch, epochs, epoch_losses[epoch - 1] / n,
                val_accs[epoch - 1],
            )

    return {
        "train_accuracies": train_accs,
        "val_accuracies": val_accs,
        "train_losses": train_losses,
        "val_losses": val_losses,
        f"train_top_{top_k}_accuracies": train_topk_accs,
        f"val_top_{top_k}_accuracies": val_topk_accs,
        "lowest_val_loss": min(val_losses) if val_losses else None,
        "highest_val_acc": max(val_accs) if val_accs else None,
        "highest_val_top_k_acc": max(val_topk_accs) if val_topk_accs else None,
    }


# Reserved key in the results blob: the sweep's config fingerprint.
# Checkpoint entries are file basenames, which can never collide with it.
SWEEP_CONFIG_KEY = "__config__"


def sweep_fingerprint(cfg: Config) -> dict:
    """The settings that define what a sweep's numbers MEAN.

    Stamped into the results blob so ``experiment.resume=true`` can refuse
    to mix result semantics (VERDICT r4 weak-item 5): resuming a centroid
    sweep with ``parameter.classifier=linear``, or flipping
    ``use_full_encoder``, would otherwise silently blend incomparable
    accuracies under one file.
    """
    return {
        "classifier": str(cfg.parameter.classifier),
        "use_full_encoder": bool(cfg.parameter.use_full_encoder),
        "epochs": int(cfg.parameter.epochs),
        "lr": float(cfg.experiment.lr),
        "decay": float(cfg.experiment.decay),
        "momentum": float(cfg.parameter.momentum),
        "seed": int(cfg.parameter.seed),
        "top_k": int(cfg.parameter.top_k),
        "dataset": str(cfg.experiment.name),
        "base_cnn": str(cfg.experiment.base_cnn),
        "d": int(cfg.parameter.d),
        # which model's checkpoints and which data the numbers describe —
        # checkpoint entries are keyed by basename, so two target dirs with
        # the same epoch=N names would otherwise collide silently
        "target_dir": str(cfg.experiment.target_dir),
        "synthetic_data": bool(cfg.select("experiment.synthetic_data", False)),
        "synthetic_size": cfg.select("experiment.synthetic_size"),
        "synthetic_noise": cfg.select("experiment.synthetic_noise"),
    }


def run_eval(cfg: Config) -> dict:
    check_eval_conf(cfg)
    mesh = mesh_from_config(cfg)
    num_classes = NUM_CLASSES[cfg.experiment.name]
    top_k = int(cfg.parameter.top_k)
    synthetic_ok = bool(cfg.select("experiment.synthetic_data", False))
    data_dir = cfg.select("experiment.data_dir")
    train_ds = load_dataset(
        cfg.experiment.name, "train", data_dir=data_dir, synthetic_ok=synthetic_ok,
        synthetic_size=cfg.select("experiment.synthetic_size"),
        synthetic_noise=cfg.select("experiment.synthetic_noise"),
    )
    val_ds = load_dataset(
        cfg.experiment.name, "test", data_dir=data_dir, synthetic_ok=synthetic_ok,
        synthetic_size=cfg.select("experiment.synthetic_size"),
        synthetic_noise=cfg.select("experiment.synthetic_noise"),
    )

    model = build_eval_model(cfg)
    use_full_encoder = bool(cfg.parameter.use_full_encoder)
    # feature-extraction chunk: per-device batches x data shards so sharded
    # device_put tiles the mesh (probe training below uses the raw per-run
    # batch, matching the reference's single-process eval loaders)
    batch = validate_per_device_batch(int(cfg.experiment.batches), mesh)
    classifier_kind = str(cfg.parameter.classifier)

    checkpoints = list_checkpoints_or_raise(str(cfg.experiment.target_dir))

    fname = str(cfg.parameter.classification_results_json_fname)
    save_dir = resolve_save_dir(cfg)
    results_path = os.path.join(save_dir, fname)

    # Incremental + resumable sweep (improvement over the reference, which
    # writes one blob at the very end, eval.py:322-325, and redoes every
    # checkpoint after a crash): results persist after EACH checkpoint, and
    # experiment.resume=true skips checkpoints already in the results file.
    # A config fingerprint stamped into the blob makes resume REFUSE a run
    # whose settings would change what the stored numbers mean — pin
    # experiment.save_dir for resumable sweeps (the default save_dir is a
    # fresh dated directory per run). Multi-process: save_dir must be a
    # shared filesystem, the same contract as checkpoint resume.
    classification_results = {}
    if bool(cfg.select("experiment.resume", False)) and os.path.exists(results_path):
        try:
            with open(results_path) as f:
                classification_results = json.load(f)
            if not isinstance(classification_results, dict):
                # valid JSON but not a results blob (null, list, string):
                # same recovery as unparseable content
                raise ValueError(
                    f"expected a JSON object, got {type(classification_results).__name__}"
                )
        except (ValueError, FileNotFoundError) as exc:
            # A corrupt results file must not silently turn "resume" into
            # "redo everything and overwrite the evidence": say why, and
            # set the original aside before the first persist() replaces
            # it. FileNotFoundError covers a shared-FS race where another
            # process's recovery renamed the file between our exists() and
            # open(); other I/O errors (EIO, EACCES) propagate loudly —
            # they are operator problems, not corruption.
            logger.warning(
                "could not use %s (%s); starting the sweep fresh — any "
                "unparseable file is kept at %s.corrupt",
                results_path, exc, results_path,
            )
            if is_logging_host():
                try:
                    os.replace(results_path, results_path + ".corrupt")
                except FileNotFoundError:
                    pass  # already renamed by a concurrent recovery
            classification_results = {}
        if classification_results:
            logger.info(
                "resuming eval sweep: %d checkpoint(s) already in %s",
                sum(1 for k in classification_results if k != SWEEP_CONFIG_KEY),
                results_path,
            )

    fingerprint = sweep_fingerprint(cfg)
    stored_fp = classification_results.get(SWEEP_CONFIG_KEY)
    if stored_fp is not None and stored_fp != fingerprint:
        diffs = {
            k: {"stored": stored_fp.get(k), "current": fingerprint.get(k)}
            for k in set(fingerprint) | set(stored_fp)
            if stored_fp.get(k) != fingerprint.get(k)
        }
        raise ValueError(
            f"refusing to resume the eval sweep at {results_path}: its "
            f"config fingerprint does not match this run, so carrying the "
            f"stored entries forward would mix incomparable results under "
            f"one file. Mismatched keys: {diffs}. Re-run with the original "
            f"settings, or point experiment.save_dir at a fresh directory."
        )
    if stored_fp is None and classification_results:
        logger.warning(
            "results file %s carries no config fingerprint (written before "
            "fingerprinting landed); adopting the current config — verify "
            "the resumed settings match the original run",
            results_path,
        )
    classification_results[SWEEP_CONFIG_KEY] = fingerprint

    def persist() -> None:
        if is_logging_host():
            os.makedirs(save_dir, exist_ok=True)
            atomic_write(
                results_path, lambda f: json.dump(classification_results, f)
            )

    for ckpt in checkpoints:
        key = os.path.basename(ckpt)
        if key in classification_results:
            logger.info("Skipping %s (already evaluated)", key)
            continue
        logger.info("Evaluation by using %s", key)
        variables = load_model_variables(ckpt)
        train_X = extract_features(
            model, variables, train_ds.images, mesh, batch, use_full_encoder
        )
        val_X = extract_features(
            model, variables, val_ds.images, mesh, batch, use_full_encoder
        )

        if classifier_kind == "centroid":
            results = centroid_probe(
                train_X, train_ds.labels, val_X, val_ds.labels, num_classes, top_k
            )
            logger.info(
                "train acc: %s, val acc: %s", results["train_acc"], results["val_acc"]
            )
        else:
            results = learnable_probe(
                cfg, classifier_kind, train_X, train_ds.labels, val_X, val_ds.labels,
                num_classes, top_k, mesh=mesh,
            )
            logger.info(
                "train acc: %s, val acc: %s",
                results["highest_val_acc"] and max(results["train_accuracies"]),
                results["highest_val_acc"],
            )
        classification_results[key] = results
        persist()

    persist()  # also covers the all-skipped resume (file carried forward)
    return classification_results


def main(argv: list[str] | None = None):
    from simclr_tpu.config import run_multirun, split_multirun_flag
    from simclr_tpu.parallel.multihost import maybe_initialize_multihost
    from simclr_tpu.utils.platform import ensure_platform

    ensure_platform()
    maybe_initialize_multihost()
    multirun, args = split_multirun_flag(list(sys.argv[1:] if argv is None else argv))
    if multirun:
        # `--multirun parameter.classifier=centroid,linear,nonlinear` sweeps
        # the probes over one checkpoint dir, one subdir per job
        return run_multirun(run_eval, "eval", args)
    cfg = load_config("eval", overrides=args)
    return run_eval(cfg)


if __name__ == "__main__":
    main()
