// Host-side batch assembly: multithreaded row gather.
//
// TPU-native replacement for the capability the reference gets from
// PyTorch's DataLoader worker processes (/root/reference/main.py:169-173,
// num_workers=8 + pin_memory): assembling a batch = gathering N rows of a
// large contiguous uint8 array by shuffled indices into one dense buffer
// that can be DMA'd to the device. Worker *processes* are the wrong shape on
// TPU hosts (one process per host under SPMD); what's actually needed is a
// memory-bandwidth-bound scatter/gather, which this does with a small thread
// pool over plain memcpy — no Python object overhead, no pickling, no IPC.
//
// Exposed as a C ABI for ctypes; built by simclr_tpu/native/build.py.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Gather rows of `src` (each `row_bytes` long) at `idx[0..n_idx)` into `dst`.
// Rows land contiguously in dst in index order. Threads split the index
// range; each thread's slice is contiguous in dst, so writes never overlap.
void gather_rows(const uint8_t* src, const int64_t* idx, uint8_t* dst,
                 int64_t n_idx, int64_t row_bytes, int32_t n_threads) {
  if (n_idx <= 0) return;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n_idx) n_threads = static_cast<int32_t>(n_idx);

  auto worker = [=](int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i) {
      std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes, row_bytes);
    }
  };

  if (n_threads == 1) {
    worker(0, n_idx);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  int64_t chunk = (n_idx + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t begin = t * chunk;
    int64_t end = begin + chunk < n_idx ? begin + chunk : n_idx;
    if (begin >= end) break;
    threads.emplace_back(worker, begin, end);
  }
  for (auto& th : threads) th.join();
}

// Gather into TWO destination buffers at once (image rows + label rows for
// the same indices) — one pass over the index list, one thread pool.
void gather_rows2(const uint8_t* src_a, int64_t row_bytes_a, uint8_t* dst_a,
                  const uint8_t* src_b, int64_t row_bytes_b, uint8_t* dst_b,
                  const int64_t* idx, int64_t n_idx, int32_t n_threads) {
  if (n_idx <= 0) return;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n_idx) n_threads = static_cast<int32_t>(n_idx);

  auto worker = [=](int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i) {
      std::memcpy(dst_a + i * row_bytes_a, src_a + idx[i] * row_bytes_a,
                  row_bytes_a);
      std::memcpy(dst_b + i * row_bytes_b, src_b + idx[i] * row_bytes_b,
                  row_bytes_b);
    }
  };

  if (n_threads == 1) {
    worker(0, n_idx);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  int64_t chunk = (n_idx + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t begin = t * chunk;
    int64_t end = begin + chunk < n_idx ? begin + chunk : n_idx;
    if (begin >= end) break;
    threads.emplace_back(worker, begin, end);
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
