"""Native (C++) host-runtime components with pure-NumPy fallbacks.

The reference's native data-path surface is PyTorch's DataLoader worker pool
(C++ core + worker processes, ``/root/reference/main.py:169-173``). On a TPU
host under SPMD there is one process, so the equivalent capability is (a) a
multithreaded C++ batch gather (``gather.cpp``) and (b) a background
prefetcher that overlaps batch assembly + H2D transfer with the device step
(``simclr_tpu/data/prefetch.py``).

Everything here degrades gracefully: if the shared library is missing and
cannot be built (no compiler), callers fall back to NumPy fancy indexing —
identical results, lower throughput.
"""

from simclr_tpu.native.lib import gather_rows, native_available

__all__ = ["gather_rows", "native_available"]
