"""ctypes bindings for the native gather library, with lazy self-build.

Build strategy: compile ``gather.cpp`` once with g++ into a per-repo cache
(``_build/libsimclr_gather.so``) on first use; any failure (no compiler,
read-only FS) flips to the NumPy fallback permanently for the process.
ctypes rather than pybind11 because this environment ships no pybind11 and
the ABI here is two flat C functions over raw pointers.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "gather.cpp")
_BUILD_DIR = os.path.join(_DIR, "_build")
_SO = os.path.join(_BUILD_DIR, "libsimclr_gather.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

DEFAULT_THREADS = min(8, os.cpu_count() or 1)


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        _SRC, "-o", _SO,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.gather_rows.argtypes = [
            u8p, i64p, u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32
        ]
        lib.gather_rows.restype = None
        lib.gather_rows2.argtypes = [
            u8p, ctypes.c_int64, u8p,
            u8p, ctypes.c_int64, u8p,
            i64p, ctypes.c_int64, ctypes.c_int32,
        ]
        lib.gather_rows2.restype = None
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def _as_u8(view: np.ndarray):
    return view.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _check_bounds(idx64: np.ndarray, n_rows: int) -> None:
    # the C path memcpy's blindly; reject anything numpy would reject (and
    # negative indices, which numpy would wrap but a raw pointer would not)
    if len(idx64) and (idx64.min() < 0 or idx64.max() >= n_rows):
        raise IndexError(
            f"gather indices out of bounds for {n_rows} rows "
            f"(min {idx64.min()}, max {idx64.max()})"
        )


def gather_rows(
    src: np.ndarray, idx: np.ndarray, n_threads: int = DEFAULT_THREADS
) -> np.ndarray:
    """``src[idx]`` for a C-contiguous array of non-negative in-range
    indices, multithreaded when native; rows are whatever trails the first
    axis. Out-of-range or negative indices raise ``IndexError`` on both the
    native and fallback paths.
    """
    lib = _load()
    src = np.ascontiguousarray(src)
    idx64 = np.ascontiguousarray(idx, dtype=np.int64)
    _check_bounds(idx64, len(src))
    if lib is None:
        return src[idx64]
    out = np.empty((len(idx64), *src.shape[1:]), dtype=src.dtype)
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    lib.gather_rows(
        _as_u8(src.view(np.uint8).reshape(-1)),
        idx64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        _as_u8(out.view(np.uint8).reshape(-1)),
        len(idx64),
        row_bytes,
        int(n_threads),
    )
    return out


def gather_rows2(
    src_a: np.ndarray,
    src_b: np.ndarray,
    idx: np.ndarray,
    n_threads: int = DEFAULT_THREADS,
) -> tuple[np.ndarray, np.ndarray]:
    """(src_a[idx], src_b[idx]) in one native pass (images + labels).

    Same bounds contract as :func:`gather_rows`.
    """
    lib = _load()
    src_a = np.ascontiguousarray(src_a)
    src_b = np.ascontiguousarray(src_b)
    idx64 = np.ascontiguousarray(idx, dtype=np.int64)
    _check_bounds(idx64, min(len(src_a), len(src_b)))
    if lib is None:
        return src_a[idx64], src_b[idx64]
    out_a = np.empty((len(idx64), *src_a.shape[1:]), dtype=src_a.dtype)
    out_b = np.empty((len(idx64), *src_b.shape[1:]), dtype=src_b.dtype)
    rb_a = src_a.dtype.itemsize * int(np.prod(src_a.shape[1:], dtype=np.int64))
    rb_b = src_b.dtype.itemsize * int(np.prod(src_b.shape[1:], dtype=np.int64))
    lib.gather_rows2(
        _as_u8(src_a.view(np.uint8).reshape(-1)), rb_a,
        _as_u8(out_a.view(np.uint8).reshape(-1)),
        _as_u8(src_b.view(np.uint8).reshape(-1)), rb_b,
        _as_u8(out_b.view(np.uint8).reshape(-1)),
        idx64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(idx64),
        int(n_threads),
    )
    return out_a, out_b
