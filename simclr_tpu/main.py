"""Contrastive pretraining entry point (SimCLR NT-Xent).

TPU-native counterpart of ``/root/reference/main.py``: where the reference
spawns one process per GPU via the vendored launcher and wraps the model in
SyncBN+DDP (``main.py:134-180``), this is ONE SPMD program — a mesh over all
chips, a jit-compiled train step (augment → two forwards → NT-Xent → psum
grads → LARS) and a host loop that only feeds raw uint8 batches and logs.

Usage (same override surface as the reference, ``README.md:17-21``):

    python -m simclr_tpu.main parameter.epochs=200 experiment.batches=512

Improvements over the reference, by design: full train-state checkpointing
with resume (the reference is save-only, SURVEY §5.3-4), and a final-epoch
checkpoint even when ``epochs % save_model_epoch != 0``.
"""

from __future__ import annotations

import itertools
import json
import math
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from simclr_tpu.config import (
    Config,
    check_pretrain_conf,
    load_config,
    resolve_save_dir,
)
from simclr_tpu.data.cifar import load_dataset
from simclr_tpu.data.pipeline import EpochIterator, epoch_index_matrix
from simclr_tpu.data.prefetch import prefetch
from simclr_tpu.models.contrastive import ContrastiveModel
from simclr_tpu.obs.anomaly import maybe_detector
from simclr_tpu.obs.compile import maybe_sentry
from simclr_tpu.obs.device import maybe_dump_oom_profile, maybe_monitor
from simclr_tpu.obs.events import EventLog
from simclr_tpu.obs.exporter import maybe_start_exporter
from simclr_tpu.obs.telemetry import Telemetry
from simclr_tpu.ops.lars import get_weight_decay_mask, lars
from simclr_tpu.parallel.compress import DEFAULT_COMM_CHUNKS, normalize_overlap
from simclr_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    enable_async_collective_flags,
    mesh_from_config,
    mesh_host_count,
    put_replicated,
    put_row_sharded,
    put_tree,
    replicated_sharding,
    validate_per_device_batch,
)
from simclr_tpu.parallel.steps import (
    check_epoch_compile_preconditions,
    make_pretrain_epoch_fn,
    make_pretrain_step,
    make_pretrain_superepoch_fn,
    superepoch_steps_from_args,
)
from simclr_tpu.parallel.train_state import create_train_state, param_count
from simclr_tpu.supervisor.guard import (
    PoisonedRun,
    PreemptedRun,
    RunGuard,
    preempt_checkpoint_name,
    resume_point,
)
from simclr_tpu.supervisor.topology import (
    check_resume_topology,
    read_topology,
    write_topology,
)
from simclr_tpu.utils.checkpoint import (
    CheckpointCorruptionError,
    checkpoint_name,
    restore_checkpoint_with_fallback,
    save_checkpoint,
)
from simclr_tpu.utils.logging import get_logger, is_logging_host
from simclr_tpu.utils.profiling import StepTimer, StepTraceWindow
from simclr_tpu.utils.schedule import calculate_initial_lr, warmup_cosine_schedule

logger = get_logger()


def _compute_dtype(cfg: Config):
    name = str(cfg.select("precision.compute_dtype", "bfloat16"))
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[name]


def build_model(cfg: Config) -> ContrastiveModel:
    return ContrastiveModel(
        base_cnn=cfg.experiment.base_cnn,
        d=cfg.parameter.d,
        cifar_stem=True,
        dtype=_compute_dtype(cfg),
        bn_cross_replica_axis=DATA_AXIS,
    )


def run_pretrain(cfg: Config) -> dict:
    """Train; returns a summary dict (final loss, steps, save_dir)."""
    check_pretrain_conf(cfg)
    seed = int(cfg.parameter.seed)

    comm_overlap = str(
        normalize_overlap(cfg.select("parallel.comm_overlap", "off"))
    )
    comm_chunks = int(cfg.select("parallel.comm_chunks", DEFAULT_COMM_CHUNKS))
    if comm_overlap == "async":
        # must land in XLA_FLAGS before mesh_from_config initializes the
        # backend; no-op off-TPU (parallel/mesh.py)
        enable_async_collective_flags()
    mesh = mesh_from_config(cfg)
    n_data = mesh.shape[DATA_AXIS]
    global_batch = validate_per_device_batch(int(cfg.experiment.batches), mesh)

    dataset = load_dataset(
        cfg.experiment.name,
        "train",
        data_dir=cfg.select("experiment.data_dir"),
        synthetic_ok=bool(cfg.select("experiment.synthetic_data", False)),
        synthetic_size=cfg.select("experiment.synthetic_size"),
        synthetic_noise=cfg.select("experiment.synthetic_noise"),
    )

    # Reference step accounting (drop_last truncation, main.py:76-80)
    steps_per_epoch = len(dataset) // global_batch
    if steps_per_epoch == 0:
        # early, before any compile; check_epoch_compile_preconditions and
        # EpochIterator repeat this at their own boundaries
        raise ValueError(
            f"dataset of {len(dataset)} samples smaller than global batch "
            f"{global_batch}"
        )
    epochs = int(cfg.parameter.epochs)
    total_steps = epochs * steps_per_epoch
    warmup_steps = int(cfg.parameter.warmup_epochs) * steps_per_epoch

    # reference parity scales the base LR by the PER-DEVICE batch
    # (lr_utils.py:11-15); 'global' scales by the full mesh-wide batch (the
    # paper's large-batch LARS recipe, conf/experiment/cifar10-large-batch)
    lr_batch = (
        global_batch
        if str(cfg.select("parameter.lr_scale_batch", "per_device")) == "global"
        else int(cfg.experiment.batches)
    )
    lr0 = calculate_initial_lr(
        float(cfg.experiment.lr),
        lr_batch,
        bool(cfg.parameter.linear_schedule),
    )
    schedule = warmup_cosine_schedule(lr0, total_steps, warmup_steps)
    tx = lars(
        schedule,
        trust_coefficient=0.001,
        weight_decay=float(cfg.experiment.decay),
        weight_decay_mask=get_weight_decay_mask(
            str(cfg.select("optimizer.weight_decay_mask", "structural")),
            str(cfg.experiment.base_cnn),
        ),
        momentum=float(cfg.parameter.momentum),
    )

    model = build_model(cfg)
    state = create_train_state(
        model, tx, jax.random.key(seed), jnp.zeros((2, 32, 32, 3), jnp.float32)
    )
    n_model = mesh.shape[MODEL_AXIS]
    if n_model > 1:
        # tensor-parallel layout from the start: head leaves sharded over the
        # model axis, everything else replicated (parallel/tp.py); also the
        # restore template, so resume keeps the layout
        from simclr_tpu.parallel.tp import tp_state_shardings

        state = put_tree(state, tp_state_shardings(mesh, state))
    else:
        state = put_tree(state, replicated_sharding(mesh))

    save_dir = resolve_save_dir(cfg)
    # run telemetry (simclr_tpu/obs/, docs/OBSERVABILITY.md): metric
    # registry + events.jsonl timeline, fed only host floats the loop
    # already fetches — scraping adds zero device syncs
    n_hosts = mesh_host_count(mesh)
    telemetry = Telemetry(
        arch=str(cfg.experiment.base_cnn),
        per_device_batch=int(cfg.experiment.batches),
        global_batch=global_batch,
        n_devices=jax.device_count(),
        mesh_hosts=n_hosts,
        d=int(cfg.parameter.d),
        grad_allreduce=str(cfg.select("parallel.grad_allreduce", "exact")),
        grad_elements=param_count(state.params),
        allreduce_devices=n_data,
        augment_impl=str(cfg.select("runtime.augment_impl", "xla")),
        comm_overlap=comm_overlap,
        comm_chunks=comm_chunks,
    )
    events = EventLog(
        save_dir,
        enabled=bool(cfg.select("telemetry.events", True)) and is_logging_host(),
    )
    # fault-tolerance guard: preemption checkpointing, heartbeat, non-finite
    # loss rollback (simclr_tpu/supervisor/, docs/FAULT_TOLERANCE.md)
    guard = RunGuard(
        save_dir,
        nan_retry_budget=int(cfg.select("supervisor.nan_retry_budget", 2)),
        telemetry=telemetry,
        events=events,
        process_index=jax.process_index(),
    )
    # step anomaly detection (obs/anomaly.py): rolling median/MAD slow-step
    # classifier + stall watchdog + rate-limited auto-trace — host clock
    # reads only, zero extra device syncs
    detector = (
        maybe_detector(cfg, save_dir, telemetry=telemetry, events=events)
        if is_logging_host() else None
    )
    # compile sentry (obs/compile.py): every lower/compile of the step
    # functions is timed, fingerprinted, and cost-analyzed; a post-warmup
    # recompile raises the alarm and reuses the detector's rate-limited
    # auto-trace. Runs on EVERY host — per-host compile/recompile counters
    # feed the fleet view (events stay logging-host-only via EventLog's
    # enabled gate)
    sentry = maybe_sentry(
        cfg, telemetry=telemetry, events=events, detector=detector
    )
    events.emit(
        "run_start", entry="pretrain", epochs=epochs,
        steps_per_epoch=steps_per_epoch, global_batch=global_batch,
        pid=os.getpid(),
    )
    start_epoch = 1
    skip_steps = 0
    # the PRIOR generation's topology record, read BEFORE this run
    # overwrites the sidecar below — the elastic remesh accept/reject input
    prior_topology = (
        read_topology(save_dir)
        if bool(cfg.select("experiment.resume", False)) else None
    )
    if bool(cfg.select("experiment.resume", False)):
        # newest checkpoint whose sha256 sidecar verifies; a corrupt latest
        # falls back to the previous one instead of failing the run
        t_restore = time.perf_counter()
        restored, ckpt = restore_checkpoint_with_fallback(save_dir, state)
        if restored is not None:
            state = restored
            telemetry.observe_restore(time.perf_counter() - t_restore)
            start_epoch, skip_steps = resume_point(
                int(state.step), steps_per_epoch
            )
            # cross-topology resume (elastic remesh): accepted only when the
            # global batch is preserved and the checkpoint sits on an epoch
            # boundary; anything else raises here, before any compile. The
            # HBM preflight is inherently revalidated — the epoch_compile
            # precondition check below runs against the CURRENT mesh.
            topology_change = check_resume_topology(
                prior_topology,
                n_devices=jax.device_count(),
                n_processes=n_hosts,
                global_batch=global_batch,
                skip_steps=skip_steps,
            )
            if topology_change is not None:
                events.emit("topology_change", **topology_change)
                logger.info(
                    "Cross-topology resume: %d -> %d devices "
                    "(%d -> %d hosts), per-device batch now %d "
                    "(global batch %d preserved)",
                    topology_change["devices_before"],
                    topology_change["devices_after"],
                    topology_change["hosts_before"],
                    topology_change["hosts_after"],
                    topology_change["per_device_batch"], global_batch,
                )
            # re-seat the timeline like pretrain_results.json below: drop
            # epoch/checkpoint events this run is about to re-emit
            events.reseat(start_epoch)
            events.emit(
                "resume", epoch=start_epoch, step=int(state.step),
                skip_steps=skip_steps, checkpoint=ckpt,
            )
            logger.info(
                "Resumed from %s at epoch %d%s", ckpt, start_epoch,
                f" (skipping {skip_steps} already-consumed steps)"
                if skip_steps else "",
            )
    if is_logging_host():
        write_topology(
            save_dir,
            n_devices=jax.device_count(),
            n_processes=n_hosts,
            global_batch=global_batch,
        )

    step_kwargs = dict(
        temperature=float(cfg.parameter.temperature),
        strength=float(cfg.experiment.strength),
        negatives=str(cfg.select("loss.negatives", "global")),
        fused=bool(cfg.select("loss.fused", False)),
        forward_mode=str(cfg.select("model.forward_mode", "two_pass")),
        remat=bool(cfg.select("model.remat", False)),
        # parallel.grad_allreduce: wire format of the data-axis gradient
        # all-reduce — exact | bf16 | int8 (parallel/compress.py,
        # docs/PERF.md §"Compressed collectives")
        grad_allreduce=str(cfg.select("parallel.grad_allreduce", "exact")),
        # parallel.comm_overlap / comm_chunks: collective schedule — "chunked"
        # splits the all-reduce into N ppermute rings XLA overlaps with the
        # backward; "async" issues those rings eagerly under the staged
        # backward (docs/PERF.md §"Async overlapped backward")
        comm_overlap=comm_overlap,
        comm_chunks=comm_chunks,
        # runtime.augment_impl: xla | fused — fused runs both views through
        # the Pallas one-VMEM-pass kernel (ops/augment_pallas.py,
        # docs/PERF.md §"Fused augmentation")
        augment_impl=str(cfg.select("runtime.augment_impl", "xla")),
        # obs/compile.py recompile sentry: the builders route the jitted
        # step through an instrumented AOT lower/compile path when set
        sentry=sentry,
    )
    epoch_compile = bool(cfg.select("runtime.epoch_compile", False))
    if epoch_compile and skip_steps:
        # epoch_compile only ever checkpoints at epoch boundaries (the scan
        # is one indivisible XLA program); a mid-epoch checkpoint must have
        # come from a per-step-mode run, which can replay the partial epoch
        raise ValueError(
            f"checkpoint at step {int(state.step)} is mid-epoch "
            f"({skip_steps}/{steps_per_epoch} steps into epoch {start_epoch}) "
            "and cannot resume under runtime.epoch_compile=true; resume with "
            "runtime.epoch_compile=false"
        )
    # runtime.epochs_per_compile=K > 1: superepochs — one XLA program per K
    # epochs (the Podracer pattern); full-K chunks cover epochs
    # 1..K*(epochs//K), the tail (< K epochs) runs on the single-epoch path
    # so every compiled program keeps one stable signature
    epochs_per_compile = int(cfg.select("runtime.epochs_per_compile", 1) or 1)
    superepoch = epoch_compile and epochs_per_compile > 1
    full_super_end = (epochs // epochs_per_compile) * epochs_per_compile

    def _check_superepoch_resume(at_epoch: int) -> None:
        """Superepoch chunks are indivisible like epochs are: a checkpoint
        inside a full-K chunk (not on a K boundary, not in the tail) cannot
        seed a resume — rejected the way mid-epoch checkpoints are above."""
        if (
            superepoch
            and at_epoch <= full_super_end
            and (at_epoch - 1) % epochs_per_compile
        ):
            raise ValueError(
                f"checkpoint at epoch {at_epoch - 1} is mid-superepoch "
                f"(epoch {(at_epoch - 1) % epochs_per_compile} of a "
                f"{epochs_per_compile}-epoch chunk) and cannot resume under "
                f"runtime.epochs_per_compile={epochs_per_compile}; resume "
                "with runtime.epochs_per_compile=1"
            )

    _check_superepoch_resume(start_epoch)
    # runtime.dataset_residency: "replicated" keeps the whole dataset in every
    # chip's HBM; "sharded" keeps N/n_data rows per data shard and reassembles
    # each step's batch with one O(global_batch) psum inside the epoch scan
    # (docs/PERF.md §"Dataset residency")
    residency = str(cfg.select("runtime.dataset_residency", "replicated"))
    put_dataset = put_replicated if residency == "replicated" else put_row_sharded
    data_shard = batch_sharding(mesh)
    # experiment.eval_every > 0: centroid-probe the test split every N
    # epochs — a REAL monitor where the reference's validation() is an
    # empty stub (/root/reference/main.py:53-58, SURVEY §2.5.6). Off by
    # default for recipe parity. Read before the builders: under
    # superepochs the probe compiles INTO the training program.
    eval_every = int(cfg.select("experiment.eval_every", 0) or 0)
    test_ds = None
    if eval_every > 0:
        test_ds = load_dataset(
            cfg.experiment.name, "test",
            data_dir=cfg.select("experiment.data_dir"),
            synthetic_ok=bool(cfg.select("experiment.synthetic_data", False)),
            synthetic_size=cfg.select("experiment.synthetic_size"),
            synthetic_noise=cfg.select("experiment.synthetic_noise"),
        )

    def _pad_rows(a: np.ndarray, mult: int) -> np.ndarray:
        """Zero-pad rows to a multiple of ``mult``. Padding appends AFTER the
        real rows, so global row indices are unchanged: training gathers
        (index < N) never see it and the monitor masks it by row position."""
        pad = -len(a) % mult
        if pad == 0:
            return a
        return np.concatenate(
            [a, np.zeros((pad,) + a.shape[1:], dtype=a.dtype)]
        )

    # superepoch in-program monitor: the centroid probe runs INSIDE the
    # compiled K-epoch program against an HBM-resident test split, so
    # monitoring costs zero extra host syncs (eval.py's host path stays the
    # parity reference and serves the tail/epoch-0 probes)
    probe_local = None
    probe_arrays: tuple = ()
    if superepoch and eval_every > 0:
        from simclr_tpu.eval import build_eval_model, make_local_centroid_monitor

        probe_local = make_local_centroid_monitor(
            build_eval_model(cfg),
            num_classes=dataset.num_classes,
            n_train=len(dataset),
            n_test=len(test_ds),
            top_k=5,
        )
    # analytic per-chip resident dataset bytes from the epoch-compile
    # preflight; the DeviceMonitor reconciles it against measured live HBM
    resident_bytes = None
    if n_model > 1:
        # tensor-parallel projection head over the model axis (parallel/tp.py).
        # Support matrix: docs/PERF.md §"Tensor-parallel support matrix"
        from simclr_tpu.parallel.tp import (
            make_pretrain_epoch_fn_tp,
            make_pretrain_step_tp,
        )

        # every loss.negatives/loss.fused variant now threads through the tp
        # builders with the dp path's dispatch (parallel/tp.py); only the
        # forward-mode restriction remains
        unsupported = {
            "model.forward_mode != two_pass": step_kwargs["forward_mode"] != "two_pass",
        }
        bad = [k for k, v in unsupported.items() if v]
        if bad:
            raise ValueError(
                f"mesh.model={n_model} (tensor parallelism) does not combine "
                f"with: {', '.join(bad)} "
                "(see docs/PERF.md, tensor-parallel support matrix)"
            )
        if epoch_compile:
            resident_bytes = check_epoch_compile_preconditions(
                len(dataset), global_batch, cfg.select("experiment.profile_dir"),
                dataset_bytes=dataset.images.nbytes,
                n_data_shards=n_data,
                residency=residency,
                epochs_per_compile=epochs_per_compile,
                steps_per_epoch=steps_per_epoch,
                probe_bytes=(
                    test_ds.images.nbytes if probe_local is not None else None
                ),
                probe_samples=len(test_ds) if probe_local is not None else 0,
            )
            epoch_fn = make_pretrain_epoch_fn_tp(
                model, tx, mesh,
                temperature=step_kwargs["temperature"],
                strength=step_kwargs["strength"],
                negatives=step_kwargs["negatives"],
                fused=step_kwargs["fused"],
                remat=step_kwargs["remat"],
                residency=residency,
                grad_allreduce=step_kwargs["grad_allreduce"],
                comm_overlap=step_kwargs["comm_overlap"],
                comm_chunks=step_kwargs["comm_chunks"],
                augment_impl=step_kwargs["augment_impl"],
            )
            if sentry is not None:
                # the TP builders predate the sentry kwarg; wrap at the
                # call site with the same epoch-scan step extractor
                epoch_fn = sentry.watch(
                    epoch_fn, "pretrain_epoch",
                    steps_from_args=lambda args: int(args[2].shape[0]),
                )
            superepoch_fn = None
            if superepoch:
                from simclr_tpu.parallel.tp import make_pretrain_superepoch_fn_tp

                superepoch_fn = make_pretrain_superepoch_fn_tp(
                    model, tx, mesh,
                    temperature=step_kwargs["temperature"],
                    strength=step_kwargs["strength"],
                    negatives=step_kwargs["negatives"],
                    fused=step_kwargs["fused"],
                    remat=step_kwargs["remat"],
                    residency=residency,
                    grad_allreduce=step_kwargs["grad_allreduce"],
                    comm_overlap=step_kwargs["comm_overlap"],
                    comm_chunks=step_kwargs["comm_chunks"],
                    augment_impl=step_kwargs["augment_impl"],
                    monitor=probe_local,
                )
                if sentry is not None:
                    # its own watched name: the K-epoch program legitimately
                    # has a different signature from the single-epoch one
                    superepoch_fn = sentry.watch(
                        superepoch_fn, "pretrain_superepoch",
                        steps_from_args=superepoch_steps_from_args(
                            2 + (3 if probe_local is not None else 0)
                        ),
                    )
            train_rows = (
                _pad_rows(dataset.images, n_data)
                if probe_local is not None and residency == "replicated"
                else dataset.images
            )
            images_all = put_dataset(train_rows, mesh)
            iterator = None
        else:
            step_fn = make_pretrain_step_tp(
                model, tx, mesh,
                temperature=step_kwargs["temperature"],
                strength=step_kwargs["strength"],
                negatives=step_kwargs["negatives"],
                fused=step_kwargs["fused"],
                remat=step_kwargs["remat"],
                grad_allreduce=step_kwargs["grad_allreduce"],
                comm_overlap=step_kwargs["comm_overlap"],
                comm_chunks=step_kwargs["comm_chunks"],
                augment_impl=step_kwargs["augment_impl"],
            )
            if sentry is not None:
                step_fn = sentry.watch(step_fn, "pretrain_step")
            iterator = EpochIterator(
                dataset, global_batch, seed=seed, shuffle=True, sharding=data_shard,
                gather_threads=int(cfg.parameter.num_workers),
            )
    elif epoch_compile:
        resident_bytes = check_epoch_compile_preconditions(
            len(dataset), global_batch, cfg.select("experiment.profile_dir"),
            dataset_bytes=dataset.images.nbytes,
            n_data_shards=n_data,
            residency=residency,
            epochs_per_compile=epochs_per_compile,
            steps_per_epoch=steps_per_epoch,
            probe_bytes=(
                test_ds.images.nbytes if probe_local is not None else None
            ),
            probe_samples=len(test_ds) if probe_local is not None else 0,
        )
        epoch_fn = make_pretrain_epoch_fn(
            model, tx, mesh, residency=residency, **step_kwargs
        )
        superepoch_fn = None
        if superepoch:
            superepoch_fn = make_pretrain_superepoch_fn(
                model, tx, mesh, residency=residency, monitor=probe_local,
                **step_kwargs,
            )
        # the uint8 dataset lives in HBM for the run (full per chip, or
        # N/n_data rows per shard under sharded residency); batches are
        # gathered on device by shuffled index inside the epoch scan.
        # both uploads are multi-host safe. With the in-program monitor under
        # replicated residency the rows are zero-padded to a multiple of the
        # data shards so each shard's probe block slices evenly; padding sits
        # after the real rows and training indices (< N) never touch it
        train_rows = (
            _pad_rows(dataset.images, n_data)
            if probe_local is not None and residency == "replicated"
            else dataset.images
        )
        images_all = put_dataset(train_rows, mesh)
        iterator = None
    else:
        step_fn = make_pretrain_step(model, tx, mesh, **step_kwargs)
        iterator = EpochIterator(
            dataset, global_batch, seed=seed, shuffle=True, sharding=data_shard,
            gather_threads=int(cfg.parameter.num_workers),
        )

    if probe_local is not None:
        # HBM-resident probe inputs for the in-program monitor: labels are
        # replicated (tiny), the test split follows the training residency.
        # Rows are padded to a multiple of the data shards so each shard owns
        # one contiguous block; the validity masks built into probe_local use
        # the REAL row counts, so padding never scores
        probe_arrays = (
            put_replicated(_pad_rows(dataset.labels, n_data), mesh),
            put_dataset(
                _pad_rows(test_ds.images, n_data)
                if residency == "replicated" else test_ds.images,
                mesh,
            ),
            put_replicated(_pad_rows(test_ds.labels, n_data), mesh),
        )

    # live HBM accounting (obs/device.py): per-device memory_stats gauges
    # sampled at scrape time from the exporter thread — host-side allocator
    # queries, zero device syncs — reconciled against the preflight's
    # analytic footprint when epoch_compile computed one
    # every host monitors its OWN local devices' HBM — per-host watermarks
    # are fleet gauges (the events stream stays logging-host-only)
    monitor = maybe_monitor(
        cfg, events=events, expected_resident_bytes=resident_bytes
    )
    if monitor is not None:
        telemetry.attach_device_monitor(monitor)

    if is_logging_host():
        os.makedirs(save_dir, exist_ok=True)
        logger.info(
            "pretrain %s: %d params, mesh %s, global batch %d (%d/device), "
            "%d steps/epoch, %d epochs, lr0 %.4f, negatives=%s",
            cfg.experiment.name, param_count(state.params), dict(mesh.shape),
            global_batch, cfg.experiment.batches, steps_per_epoch, epochs, lr0,
            cfg.select("loss.negatives", "global"),
        )

    base_key = jax.random.key(seed + 1)
    metrics = {"loss": jnp.zeros(())}
    save_model_epoch = int(cfg.experiment.save_model_epoch)
    monitor_val_acc = None
    # per-epoch evidence curves (loss always; monitor when eval_every>0) as
    # [epoch, value] pairs — self-describing under resume, where the run
    # covers start_epoch..epochs only. Persisted to
    # <save_dir>/pretrain_results.json so a long run leaves a committable
    # learning artifact, not just a final scalar.
    loss_history: list[list[float]] = []
    monitor_history: list[list[float]] = []
    if start_epoch > 1:
        # Re-seat the persisted curves at the resume point so this run
        # appends [epoch, value] rows without duplicating restored epochs:
        # rows at or past start_epoch are about to be re-run (the resumed
        # checkpoint may be older than the last logged epoch) and are
        # dropped; everything earlier — including the epoch-0 random-init
        # probe — carries over.
        prior_path = os.path.join(save_dir, "pretrain_results.json")
        if os.path.exists(prior_path):
            try:
                with open(prior_path) as f:
                    prior = json.load(f)
            except ValueError:
                prior = {}
            loss_history = [
                r for r in prior.get("loss_history", []) if r[0] < start_epoch
            ]
            monitor_history = [
                r for r in prior.get("monitor_history", []) if r[0] < start_epoch
            ]

    def write_results(summary: dict) -> None:
        """Persist the run summary/curves; called every epoch (not just at
        the end) so a preempted or crashed run leaves its history for the
        resume to re-seat."""
        if not is_logging_host():
            return
        from simclr_tpu.utils.ioutil import atomic_write

        atomic_write(
            os.path.join(save_dir, "pretrain_results.json"),
            lambda f: json.dump(summary, f, indent=1),
        )

    if eval_every > 0:
        # host-side probe: used every eval_every epochs on the per-step and
        # single-epoch paths, and for the epoch-0/tail probes under
        # superepochs (in-chunk probes run inside the compiled program)
        # on-device reshard to replicated: the encode program expects
        # replicated variables, and a TP run's live head leaves are
        # model-sharded global arrays that span non-addressable devices
        # under multi-process (a bare host fetch would raise). The jitted
        # identity's out_shardings makes XLA do the all-gather; the
        # replicated outputs feed the encode jit directly — no host round
        # trip.
        gather_replicated = jax.jit(
            lambda t: t, out_shardings=replicated_sharding(mesh)
        )
        # the shared f32 extraction model — the monitor's accuracy is
        # directly comparable to a post-hoc eval.py centroid run on the
        # same checkpoint regardless of the training compute dtype
        from simclr_tpu.eval import build_eval_model, centroid_probe, extract_features

        monitor_model = build_eval_model(cfg)

        def run_monitor_probe(epoch: int) -> float:
            variables = gather_replicated(
                {"params": state.params, "batch_stats": state.batch_stats}
            )
            train_X = extract_features(
                monitor_model, variables, dataset.images, mesh, global_batch, False
            )
            val_X = extract_features(
                monitor_model, variables, test_ds.images, mesh, global_batch, False
            )
            res = centroid_probe(
                train_X, dataset.labels, val_X, test_ds.labels,
                dataset.num_classes, top_k=5,
            )
            telemetry.observe_val_acc(res["val_acc"])
            if is_logging_host():
                logger.info(
                    "Epoch:%d centroid probe: val top-1 %.4f (top-5 %.4f)",
                    epoch, res["val_acc"], res["val_top_5_acc"],
                )
            return res["val_acc"]
    if eval_every > 0 and start_epoch == 1 and not monitor_history:
        # epoch-0 probe: the RANDOM-INIT accuracy anchors the monitor curve,
        # so a later reader can tell learned features from data that is
        # already separable to an untrained encoder (skipped when a re-seated
        # history already carries it)
        monitor_history.append([0, run_monitor_probe(0)])
    # host-side step counter: reading state.step off-device every iteration
    # would sync the host to the in-flight step and kill async dispatch
    cur_step = (start_epoch - 1) * steps_per_epoch + skip_steps
    # steady-state trace window: skips the first (compiling) step
    tracer = StepTraceWindow(
        cfg.select("experiment.profile_dir"),
        start=cur_step + 2,
        length=int(cfg.select("experiment.profile_steps", 10) or 10),
        enabled=is_logging_host(),
    )
    t_start = time.time()
    # steady-state throughput, excluding the first (compiling) steps; the
    # per-epoch log line reports the cumulative rate instead. In
    # epoch_compile mode one tick covers a whole epoch of steps; under
    # superepochs one tick covers K epochs (tail epochs, a different
    # program, skip the timer — mixed tick sizes would skew the rate)
    imgs_per_tick = global_batch
    if epoch_compile:
        imgs_per_tick = global_batch * steps_per_epoch
        if superepoch:
            imgs_per_tick *= epochs_per_compile
    timer = StepTimer(imgs_per_tick, warmup=1 if epoch_compile else 3)
    stem = str(cfg.experiment.output_model_name)
    # per-host /metrics + /debug/trace exporter; None unless telemetry.port
    # (or telemetry.ready_file for an ephemeral port) is configured. Every
    # process runs one — process i>0 publishes telemetry.p<i>.ready — so
    # the supervisor's FleetCollector sees the whole fleet
    exporter = maybe_start_exporter(
        cfg, telemetry, save_dir, process_index=jax.process_index()
    )
    guard.install_signals()
    try:
        epoch = start_epoch
        while epoch <= epochs:
            epoch_start_step = cur_step
            epoch_t0 = time.perf_counter()
            # full-K superepoch chunk: one compiled call runs K epochs (and
            # their probes) on device; the host only syncs here, at the
            # boundary, to fetch the stacked per-epoch metrics. The tail
            # (epochs past the last full chunk) falls through to the
            # single-epoch program below.
            if (
                superepoch
                and (epoch - 1) % epochs_per_compile == 0
                and epoch + epochs_per_compile - 1 <= epochs
            ):
                K = epochs_per_compile
                chunk = list(range(epoch, epoch + K))
                boundary = chunk[-1]
                idx_super = jnp.asarray(
                    np.stack([
                        epoch_index_matrix(
                            len(dataset), seed, e, steps_per_epoch, global_batch
                        )
                        for e in chunk
                    ])
                )
                if probe_local is not None:
                    probed = [e % eval_every == 0 or e == epochs for e in chunk]
                    state, hist = superepoch_fn(
                        state, images_all, *probe_arrays,
                        idx_super, jnp.asarray(probed), base_key, cur_step,
                    )
                else:
                    probed = [False] * K
                    state, hist = superepoch_fn(
                        state, images_all, idx_super, base_key, cur_step
                    )
                metrics = {"loss": hist["loss"][-1, -1]}
                timer.tick(hist["loss"])
                # the boundary fetch: K epochs of losses (and probe rows)
                # come back in one transfer of K*steps_per_epoch floats
                hist = jax.device_get(hist)
                losses = np.asarray(hist["loss"])
                cur_step += K * steps_per_epoch
                if detector is not None:
                    detector.tick(cur_step, boundary)
                    detector.pause()
                if guard.preempt_requested:
                    # same boundary-checkpoint contract as below; cur_step is
                    # a multiple of steps_per_epoch so this lands as the
                    # regular boundary checkpoint name
                    timer.pause(metrics["loss"])
                    path = os.path.join(
                        save_dir,
                        preempt_checkpoint_name(cur_step, steps_per_epoch, stem),
                    )
                    t_save = time.perf_counter()
                    save_checkpoint(path, state)
                    telemetry.observe_save(time.perf_counter() - t_save)
                    events.emit(
                        "preempt", step=cur_step, epoch=boundary, checkpoint=path
                    )
                    guard.beat_preempted(cur_step, boundary)
                    raise PreemptedRun(path)
                chunk_losses = [float(losses[j, -1]) for j in range(K)]
                # checked_loss is the fault-injection seam on the single-epoch
                # path; route the boundary loss through it so injected NaNs
                # still poison superepoch runs
                chunk_losses[-1] = guard.checked_loss(cur_step, chunk_losses[-1])
                epoch_loss = chunk_losses[-1]
                dt = time.perf_counter() - epoch_t0
                # per-host telemetry on EVERY host (the fleet skew gauge
                # divides per-host step times): all inputs are host floats
                # already in hand, so this adds no device syncs anywhere
                for j, e in enumerate(chunk):
                    step_e = epoch_start_step + (j + 1) * steps_per_epoch
                    telemetry.observe_epoch(
                        e,
                        epochs=epochs,
                        step=step_e,
                        steps=steps_per_epoch,
                        seconds=dt / K,
                        loss=chunk_losses[j],
                        lr=float(schedule(max(step_e - 1, 0))),
                    )
                guard.beat(cur_step, boundary, loss=epoch_loss)
                if any(not math.isfinite(l) for l in chunk_losses):
                    # same rollback as the single-epoch path; under
                    # superepochs every checkpoint lands on a K boundary, so
                    # the resume point realigns (validated below — a stale
                    # mid-chunk checkpoint from a K=1 run cannot seed this)
                    first_bad = next(
                        l for l in chunk_losses if not math.isfinite(l)
                    )
                    try:
                        t_restore = time.perf_counter()
                        restored, rpath = restore_checkpoint_with_fallback(
                            save_dir, state
                        )
                    except CheckpointCorruptionError as e:
                        raise PoisonedRun(str(e)) from e
                    guard.record_rollback(first_bad, rpath)
                    telemetry.observe_restore(time.perf_counter() - t_restore)
                    state = restored
                    cur_step = int(state.step)
                    epoch, skip_steps = resume_point(cur_step, steps_per_epoch)
                    _check_superepoch_resume(epoch)
                    loss_history = [r for r in loss_history if r[0] < epoch]
                    monitor_history = [r for r in monitor_history if r[0] < epoch]
                    events.reseat(epoch)
                    base_key = jax.random.fold_in(
                        jax.random.key(seed + 1), guard.nan_rollbacks
                    )
                    continue
                if is_logging_host():
                    lr_now = float(schedule(max(cur_step - 1, 0)))
                    imgs_per_sec = (
                        (cur_step - (start_epoch - 1) * steps_per_epoch)
                        * global_batch / max(time.time() - t_start, 1e-9)
                    )
                    logger.info(
                        "Epoch:%d/%d progress:%.3f loss:%.3f, lr:%.7f, "
                        "imgs/sec:%.0f (superepoch of %d)",
                        boundary, epochs, boundary / epochs, epoch_loss,
                        lr_now, imgs_per_sec, K,
                    )
                # per-epoch rows reconstructed from the stacked metrics:
                # results/events keep the exact shape K=1 produces
                for j, e in enumerate(chunk):
                    step_e = epoch_start_step + (j + 1) * steps_per_epoch
                    loss_history.append([e, chunk_losses[j]])
                    events.emit(
                        "epoch", epoch=e, step=step_e, loss=chunk_losses[j],
                        seconds=round(dt / K, 6),
                    )
                    if probed[j]:
                        monitor_val_acc = float(hist["monitor/val_acc"][j])
                        telemetry.observe_val_acc(monitor_val_acc)
                        if is_logging_host():
                            logger.info(
                                "Epoch:%d centroid probe: val top-1 %.4f "
                                "(top-5 %.4f)",
                                e, monitor_val_acc,
                                float(hist["monitor/val_top_5_acc"][j]),
                            )
                        monitor_history.append([e, monitor_val_acc])
                if (
                    any(e % save_model_epoch == 0 for e in chunk)
                    or boundary == epochs
                ):
                    path = os.path.join(save_dir, checkpoint_name(boundary, stem))
                    timer.pause(metrics["loss"])
                    t_save = time.perf_counter()
                    save_checkpoint(path, state)
                    telemetry.observe_save(time.perf_counter() - t_save)
                    events.emit("checkpoint", epoch=boundary, path=path)
                    guard.after_save(boundary, path)
                    timer.resume()
                write_results(
                    {
                        "epochs": epochs,
                        "save_dir": save_dir,
                        "loss_history": loss_history,
                        "monitor_history": monitor_history,
                        "complete": False,
                    }
                )
                epoch += K
                continue
            if epoch_compile:
                idx_e = jnp.asarray(
                    epoch_index_matrix(
                        len(dataset), seed, epoch, steps_per_epoch, global_batch
                    )
                )
                state, hist = epoch_fn(state, images_all, idx_e, base_key, cur_step)
                metrics = {"loss": hist["loss"][-1]}
                if not superepoch:
                    # under superepochs this path only runs tail epochs; the
                    # timer's tick unit is K epochs, so tail epochs stay out
                    timer.tick(hist["loss"])
                cur_step += steps_per_epoch
                if detector is not None:
                    # one tick per epoch here: the detector's "step" unit is
                    # whatever the host loop's unit of progress is
                    detector.tick(cur_step, epoch)
            else:
                batches = iterator.batches(epoch)
                if skip_steps:
                    # mid-epoch resume: replay the epoch's deterministic
                    # batch order past the consumed prefix; step RNG folds on
                    # the absolute cur_step, so the continuation is exact
                    batches = itertools.islice(batches, skip_steps, None)
                    skip_steps = 0
                for batch in prefetch(batches):
                    tracer.tick(cur_step, pending=metrics["loss"])
                    step_rng = jax.random.fold_in(base_key, cur_step)
                    state, metrics = step_fn(state, batch["image"], step_rng)
                    timer.tick(metrics["loss"])
                    cur_step += 1
                    if detector is not None:
                        # BEFORE the beat: the beat is where fault injection
                        # wedges, and the watchdog must already be armed to
                        # catch exactly that class of hang
                        detector.tick(cur_step, epoch)
                    guard.beat(cur_step, epoch)
                    if guard.preempt_requested:
                        break
            if detector is not None:
                # epoch-boundary work (probe, checkpoint I/O, preempt saves)
                # is not a step: disarm so it can never read as a stall, and
                # keep its duration out of the step-time window
                detector.pause()
            if guard.preempt_requested:
                # land a resumable checkpoint at this step boundary, then
                # exit 75 via main() — at an exact epoch boundary this is the
                # regular boundary checkpoint; mid-epoch it gets "-preempt"
                timer.pause(metrics["loss"])
                epoch_loss = float(metrics["loss"])
                if (
                    cur_step == epoch * steps_per_epoch
                    and math.isfinite(epoch_loss)
                    and (not loss_history or loss_history[-1][0] < epoch)
                ):
                    # the preempt landed on a completed epoch (elastic
                    # grow-back drains SIGTERM at exactly this boundary):
                    # its loss row and epoch event are in hand — persist
                    # them, or the resumed run's history skips this epoch
                    loss_history.append([epoch, epoch_loss])
                    events.emit(
                        "epoch", epoch=epoch, step=cur_step, loss=epoch_loss,
                        seconds=round(time.perf_counter() - epoch_t0, 6),
                    )
                    write_results(
                        {
                            "epochs": epochs,
                            "save_dir": save_dir,
                            "loss_history": loss_history,
                            "monitor_history": monitor_history,
                            "complete": False,
                        }
                    )
                path = os.path.join(
                    save_dir,
                    preempt_checkpoint_name(cur_step, steps_per_epoch, stem),
                )
                t_save = time.perf_counter()
                save_checkpoint(path, state)
                telemetry.observe_save(time.perf_counter() - t_save)
                events.emit(
                    "preempt", step=cur_step, epoch=epoch, checkpoint=path
                )
                guard.beat_preempted(cur_step, epoch)
                raise PreemptedRun(path)

            epoch_loss = guard.checked_loss(cur_step, float(metrics["loss"]))
            # epoch telemetry BEFORE the boundary beat, so the beat's
            # snapshot (and any scrape) reflects the epoch that just
            # finished; every input is a host float already in hand, and
            # every host updates its OWN gauges for the fleet view
            telemetry.observe_epoch(
                epoch,
                epochs=epochs,
                step=cur_step,
                steps=cur_step - epoch_start_step,
                seconds=time.perf_counter() - epoch_t0,
                loss=epoch_loss,
                lr=float(schedule(max(cur_step - 1, 0))),
            )
            guard.beat(cur_step, epoch, loss=epoch_loss)
            if not math.isfinite(epoch_loss):
                # roll back to the newest verified checkpoint; a different
                # RNG stream on the retry — deterministically replaying the
                # same trajectory would reproduce the same divergence
                try:
                    t_restore = time.perf_counter()
                    restored, rpath = restore_checkpoint_with_fallback(
                        save_dir, state
                    )
                except CheckpointCorruptionError as e:
                    raise PoisonedRun(str(e)) from e
                guard.record_rollback(epoch_loss, rpath)
                telemetry.observe_restore(time.perf_counter() - t_restore)
                state = restored
                cur_step = int(state.step)
                epoch, skip_steps = resume_point(cur_step, steps_per_epoch)
                loss_history = [r for r in loss_history if r[0] < epoch]
                monitor_history = [r for r in monitor_history if r[0] < epoch]
                # the rolled-back epochs re-run: re-seat the timeline too so
                # their epoch/checkpoint events are not duplicated
                events.reseat(epoch)
                base_key = jax.random.fold_in(
                    jax.random.key(seed + 1), guard.nan_rollbacks
                )
                continue
            if is_logging_host():
                # one line per epoch, the reference's rank-0 log (main.py:124-127)
                lr_now = float(schedule(max(cur_step - 1, 0)))
                imgs_per_sec = (
                    (cur_step - (start_epoch - 1) * steps_per_epoch)
                    * global_batch / max(time.time() - t_start, 1e-9)
                )
                logger.info(
                    "Epoch:%d/%d progress:%.3f loss:%.3f, lr:%.7f, imgs/sec:%.0f",
                    epoch, epochs, epoch / epochs, epoch_loss, lr_now,
                    imgs_per_sec,
                )
            loss_history.append([epoch, epoch_loss])
            events.emit(
                "epoch", epoch=epoch, step=cur_step, loss=epoch_loss,
                seconds=round(time.perf_counter() - epoch_t0, 6),
            )
            if eval_every > 0 and (epoch % eval_every == 0 or epoch == epochs):
                timer.pause(metrics["loss"])  # keep probe compute out of imgs/sec
                monitor_val_acc = run_monitor_probe(epoch)
                monitor_history.append([epoch, monitor_val_acc])
                timer.resume()
            if epoch % save_model_epoch == 0 or epoch == epochs:
                path = os.path.join(save_dir, checkpoint_name(epoch, stem))
                timer.pause(metrics["loss"])  # keep save I/O out of the imgs/sec window
                t_save = time.perf_counter()
                save_checkpoint(path, state)
                telemetry.observe_save(time.perf_counter() - t_save)
                events.emit("checkpoint", epoch=epoch, path=path)
                guard.after_save(epoch, path)
                timer.resume()
            write_results(
                {
                    "epochs": epochs,
                    "save_dir": save_dir,
                    "loss_history": loss_history,
                    "monitor_history": monitor_history,
                    "complete": False,
                }
            )
            epoch += 1
    except Exception as exc:
        # an allocator RESOURCE_EXHAUSTED leaves its forensic behind —
        # device memory profile + oom event — before the error propagates;
        # any other exception passes through untouched
        if is_logging_host():
            maybe_dump_oom_profile(save_dir, exc, events=events)
        raise
    finally:
        guard.restore_signals()
        if detector is not None:
            detector.close()
        if exporter is not None:
            exporter.close()

    tracer.close(pending=metrics["loss"])
    throughput = timer.summary()
    if is_logging_host() and throughput["steps"] > 0:
        # in epoch_compile mode the timer ticks once per EPOCH (once per K
        # epochs under superepochs); report steps
        timed_steps = throughput["steps"] * (
            steps_per_epoch * (epochs_per_compile if superepoch else 1)
            if epoch_compile else 1
        )
        logger.info(
            "steady-state: %.0f imgs/sec (%.0f per chip) over %d steps",
            throughput["imgs_per_sec"], throughput["imgs_per_sec_per_chip"],
            timed_steps,
        )
    summary = {
        "final_loss": float(metrics["loss"]),
        "steps": int(state.step),
        "epochs": epochs,
        "save_dir": save_dir,
        "global_batch": global_batch,
        "n_data_shards": n_data,
        "lr0": lr0,
        "imgs_per_sec_steady": throughput["imgs_per_sec"],
    }
    summary["loss_history"] = loss_history
    summary["complete"] = True
    if monitor_history:
        summary["monitor_history"] = monitor_history
    if monitor_val_acc is None and monitor_history:
        # resumed with nothing left to run: the last re-seated probe stands
        monitor_val_acc = monitor_history[-1][1]
    if monitor_val_acc is not None:
        summary["monitor_val_acc"] = monitor_val_acc
    write_results(summary)
    events.emit("run_end", step=int(state.step), loss=summary["final_loss"])
    return summary


def main(argv: list[str] | None = None):
    from simclr_tpu.config import run_multirun, split_multirun_flag
    from simclr_tpu.parallel.multihost import maybe_initialize_multihost
    from simclr_tpu.supervisor.guard import EXIT_POISONED, EXIT_PREEMPTED
    from simclr_tpu.utils.platform import ensure_platform

    ensure_platform()
    maybe_initialize_multihost()
    multirun, args = split_multirun_flag(list(sys.argv[1:] if argv is None else argv))
    # exit-code contract (docs/FAULT_TOLERANCE.md): 75 = preempted but
    # resumable (the supervisor restarts with resume=true), 76 = poisoned
    # (restarting cannot help; the supervisor gives up)
    try:
        if multirun:
            return run_multirun(run_pretrain, "config", args)
        cfg = load_config("config", overrides=args)
        return run_pretrain(cfg)
    except PreemptedRun as e:
        logger.info("%s", e)
        sys.exit(EXIT_PREEMPTED)
    except PoisonedRun as e:
        logger.error("%s", e)
        sys.exit(EXIT_POISONED)


if __name__ == "__main__":
    main()
