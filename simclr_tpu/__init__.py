"""simclr_tpu — a TPU-native SimCLR framework (JAX/XLA/pjit/Pallas).

A from-scratch re-design of the capabilities of nzw0301/SimCLR (multi-GPU
PyTorch SimCLR for CIFAR-10/100) for TPU hardware: one SPMD program per entry
point, jit-compiled train steps over a `jax.sharding.Mesh`, XLA collectives
over ICI instead of NCCL, global-batch BatchNorm instead of SyncBN, and an
optional all-gathered global negative set for NT-Xent.

Entry points (module-level, mirroring the reference CLI):
  python -m simclr_tpu.main          # contrastive pretraining
  python -m simclr_tpu.eval          # frozen-feature probes (centroid/linear/nonlinear)
  python -m simclr_tpu.supervised    # fully-supervised baseline
  python -m simclr_tpu.save_features # feature export (.npy)
"""

from simclr_tpu.config import Config, ConfigError, load_config
from simclr_tpu.utils.platform import ensure_platform

# Re-apply JAX_PLATFORMS before any submodule touches a device: environments
# that pin a platform in sitecustomize otherwise override the env var (see
# utils/platform.py). Must run at package import, ahead of lazy backend init.
ensure_platform()

__version__ = "0.1.0"

__all__ = ["Config", "ConfigError", "load_config", "__version__"]
