"""CLI: ``python -m simclr_tpu.coscheduler --nprocs N --devices-per-proc D
[--force-cpu] [--coord-timeout-s T] -- <overrides...>``.

Loads ``conf/cosched.yaml`` (which composes the full pretrain root, so
every training override works unchanged), validates the co-scheduling
surface, and runs :class:`~simclr_tpu.coscheduler.core.CoScheduler`.
Overrides in the ``serve.*``/``cosched.*`` namespaces configure this
process only; everything else is forwarded to the training children.
Prints the run summary as one JSON line (the same contract as
``python -m simclr_tpu.supervisor.elastic``).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys


def main(argv: list[str] | None = None) -> int:
    from simclr_tpu.config import (
        ConfigError,
        check_cosched_conf,
        load_config,
        resolve_save_dir,
    )

    parser = argparse.ArgumentParser(
        prog="python -m simclr_tpu.coscheduler",
        description="Continuous train+serve co-scheduler: supervised "
        "pretraining + checkpoint-hot-reloading serve tier on one pod.",
    )
    parser.add_argument(
        "--nprocs", type=int, required=True,
        help="training hosts (JAX processes) in the full topology",
    )
    parser.add_argument(
        "--devices-per-proc", type=int, required=True,
        help="accelerator devices per training host (batch-rescale math)",
    )
    parser.add_argument(
        "--force-cpu", action="store_true",
        help="virtual CPU devices for children AND the serve tier (dryrun)",
    )
    parser.add_argument(
        "--coord-timeout-s", type=float, default=None,
        help="rendezvous fail-fast deadline exported to every child",
    )
    parser.add_argument("rest", nargs=argparse.REMAINDER)
    args = parser.parse_args(sys.argv[1:] if argv is None else argv)

    overrides = list(args.rest)
    if overrides and overrides[0] == "--":
        overrides = overrides[1:]

    try:
        cfg = load_config("cosched", overrides=overrides)
        check_cosched_conf(cfg)
        save_dir = resolve_save_dir(cfg)
    except ConfigError as e:
        print(f"coscheduler: {e}", file=sys.stderr)
        return 2
    if not cfg.select("experiment.save_dir"):
        cfg.update_dotted("experiment.save_dir", save_dir, allow_new=True)

    if args.force_cpu:
        # the serve tier lives in THIS process and needs its own virtual
        # device slice, sized for the fully-grown tier; must land before
        # the first jax import (children get theirs via group_env)
        max_serve = int(
            cfg.select(
                "cosched.max_serve_devices",
                cfg.select("cosched.serve_devices", 1),
            )
        )
        os.environ["JAX_PLATFORMS"] = "cpu"
        flag = f"--xla_force_host_platform_device_count={max_serve}"
        xla_flags = " ".join(
            part
            for part in os.environ.get("XLA_FLAGS", "").split()
            if not part.startswith("--xla_force_host_platform_device_count=")
        )
        os.environ["XLA_FLAGS"] = (xla_flags + " " + flag).strip()

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    from simclr_tpu.coscheduler.core import CoScheduler

    # serve./cosched. keys configure this process; the training children's
    # strict pretrain config would reject them
    train_overrides = [
        o
        for o in overrides
        if o.split("=", 1)[0].lstrip("+").split(".")[0]
        not in ("serve", "cosched")
    ]
    try:
        co = CoScheduler(
            cfg,
            nprocs=args.nprocs,
            devices_per_proc=args.devices_per_proc,
            force_cpu=args.force_cpu,
            coord_timeout_s=args.coord_timeout_s,
            train_overrides=train_overrides,
        )
    except ConfigError as e:
        print(f"coscheduler: {e}", file=sys.stderr)
        return 2
    summary = co.run()
    print(json.dumps(summary), flush=True)
    if summary.get("outcome") == "clean":
        return 0
    return int(summary.get("exit", 1) or 1)


if __name__ == "__main__":
    sys.exit(main())
