"""Continuous train+serve co-scheduler (``python -m simclr_tpu.coscheduler``).

Runs contrastive pretraining and the embedding serve tier as ONE
supervised system on one device pod: the serve tier starts on random
generation-0 weights, hot-reloads every sha256-verified checkpoint the
run writes with a zero-downtime generation swap (and a generation-tagged
retrieval-corpus re-embed), and elastic reallocation moves a host between
the training mesh and the serve tier as queue pressure demands. See
``docs/SERVING.md`` ("Continuous reload") and ``conf/cosched.yaml``.

Import surface: :class:`ReallocationPolicy` (jax-free) is imported
eagerly; the jax-heavy :class:`CoScheduler` / :class:`ReloadManager` load
lazily so config validation and policy unit tests stay cheap.
"""

from __future__ import annotations

from simclr_tpu.coscheduler.policy import (
    RELEASE,
    SHRINK,
    ReallocationPolicy,
    pressure_of,
)

__all__ = [
    "RELEASE",
    "SHRINK",
    "CoScheduler",
    "ReallocationPolicy",
    "ReloadManager",
    "pressure_of",
]


def __getattr__(name: str):
    if name == "CoScheduler":
        from simclr_tpu.coscheduler.core import CoScheduler

        return CoScheduler
    if name == "ReloadManager":
        from simclr_tpu.coscheduler.reload import ReloadManager

        return ReloadManager
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
