"""Co-scheduler core: pretraining + the serve tier as one supervised system.

``python -m simclr_tpu.coscheduler`` runs three cooperating planes in the
coordinator process:

  * **train** — an :class:`~simclr_tpu.supervisor.elastic.ElasticSupervisor`
    on a background thread, launching the usual per-host training children
    (``simclr_tpu.main``) with every serve/cosched override filtered out;
  * **serve** — the full HTTP stack (ReplicaPool over
    ``cosched.serve_devices`` local devices, DynamicBatcher, EmbedServer)
    in-process, starting on random generation-0 weights and hot-reloading
    each sha256-verified checkpoint the run writes
    (:class:`~simclr_tpu.coscheduler.reload.ReloadManager`);
  * **policy** — a pressure sampler feeding
    :class:`~simclr_tpu.coscheduler.policy.ReallocationPolicy`: sustained
    queue pressure lends a training host to the serve tier (a deliberate
    remesh-on-loss shrink + a new serve replica), ebbing traffic retires
    the extra replica and grows training back.

The training run dir is the single rendezvous surface: checkpoints flow
train->serve through it, events.jsonl interleaves supervisor lifecycle
with swap/reallocation events, and ``serve.ready`` publishes the bound
endpoint next to the telemetry ready files (auto-discovered by the fleet
collector).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import sys
import threading
import time

import numpy as np

from simclr_tpu.config import ConfigError, resolve_save_dir
from simclr_tpu.coscheduler.policy import (
    RELEASE,
    SHRINK,
    ReallocationPolicy,
    pressure_of,
)
from simclr_tpu.coscheduler.reload import ReloadManager
from simclr_tpu.obs.events import EventLog
from simclr_tpu.utils.ioutil import atomic_write

logger = logging.getLogger("simclr_tpu.coscheduler")

_POLICY_POLL_S = 0.25


class CoScheduler:
    """Wire the three planes together over one run dir; see module docs.

    ``train_overrides`` is the already-filtered override list for the
    training children (no ``serve.*``/``cosched.*`` keys — those configure
    this process, and ``simclr_tpu.main``'s strict config would reject
    them).
    """

    def __init__(
        self,
        cfg,
        *,
        nprocs: int,
        devices_per_proc: int,
        force_cpu: bool = False,
        coord_timeout_s: float | None = None,
        train_overrides: list[str] | None = None,
    ):
        self.cfg = cfg
        self.nprocs = int(nprocs)
        self.devices_per_proc = int(devices_per_proc)
        self.force_cpu = bool(force_cpu)
        self.coord_timeout_s = coord_timeout_s
        self.train_overrides = list(train_overrides or [])
        self.serve_devices = int(cfg.select("cosched.serve_devices", 1))
        self.max_serve_devices = int(
            cfg.select("cosched.max_serve_devices", self.serve_devices)
        )
        per_device = int(cfg.select("experiment.batches", 0) or 0)
        if per_device <= 0:
            raise ConfigError(
                f"experiment.batches must be a positive per-device batch, "
                f"got {per_device!r}"
            )
        self.global_batch = per_device * self.devices_per_proc * self.nprocs
        # populated by run(); held as attributes so the policy handlers and
        # tests can reach the live stack
        self.pool = None
        self.batcher = None
        self.server = None
        self.metrics = None
        self.reload = None
        self.supervisor = None
        self.events = None
        self._model = None

    # -- serve plane ---------------------------------------------------------
    def _build_serve_stack(self, save_dir: str):
        import jax
        import jax.numpy as jnp

        from simclr_tpu.eval import build_eval_model
        from simclr_tpu.serve.metrics import ServeMetrics
        from simclr_tpu.serve.replica import ReplicaPool
        from simclr_tpu.serve.server import _write_ready_file, start_server

        cfg = self.cfg
        seed = int(cfg.parameter.seed)
        self._model = model = build_eval_model(cfg)
        # generation 0: random-init weights with the checkpoint's exact
        # variable structure (same model builder eval uses), so the first
        # real checkpoint stages shape-identically — zero recompiles
        variables = jax.tree.map(
            np.asarray,
            model.init(jax.random.key(seed), jnp.zeros((2, 32, 32, 3))),
        )
        self.metrics = metrics = ServeMetrics()
        logger.info(
            "building %d serve replica(s) on generation-0 weights...",
            self.serve_devices,
        )
        self.pool = pool = ReplicaPool.from_model(
            model,
            variables,
            replicas=self.serve_devices,
            max_batch=int(cfg.serve.max_batch),
            use_full_encoder=bool(cfg.parameter.use_full_encoder),
            metrics=metrics,
            warmup=True,
            weights=str(cfg.select("serve.weights", "exact")),
        )
        metrics.weights_generation.set(0)
        self.server, self.batcher = start_server(cfg, pool=pool, metrics=metrics)

        n_corpus = int(cfg.select("cosched.corpus_images", 0) or 0)
        corpus_images = None
        if n_corpus > 0:
            # deterministic synthetic corpus: what matters is that every
            # generation re-embeds the SAME rows, so /v1/neighbors answers
            # track the encoder, not the data
            rng = np.random.default_rng(seed)
            corpus_images = rng.integers(
                0, 256, size=(n_corpus, 32, 32, 3), dtype=np.uint8
            )
        self.reload = ReloadManager(
            pool,
            save_dir=save_dir,
            server=self.server,
            events=self.events,
            metrics=metrics,
            corpus_images=corpus_images,
            reembed_batch=int(cfg.select("cosched.reembed_batch", 256)),
            neighbors_metric=str(cfg.select("serve.neighbors_metric", "dot")),
            corpus_dtype=str(cfg.select("serve.corpus_dtype", "fp32")),
            ann_cells=int(cfg.select("serve.ann_cells", 0) or 0),
            ann_probe=int(cfg.select("serve.ann_probe", 1) or 1),
            poll_s=float(cfg.select("cosched.reload_poll_s", 2.0)),
        )
        self.reload.current_variables = variables
        self.reload.bootstrap_corpus()

        server_thread = threading.Thread(
            target=self.server.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="cosched-serve",
            daemon=True,
        )
        server_thread.start()
        _write_ready_file(cfg, self.server)
        host, port = self.server.server_address[:2]
        logger.info("serve tier up on http://%s:%d", host, port)
        return server_thread

    # -- elastic reallocation handlers ---------------------------------------
    def _grow_serve(self, now: float, policy: ReallocationPolicy) -> None:
        """SHRINK: lend one training host, add one serve replica."""
        import jax

        from simclr_tpu.serve.engine import EmbedEngine

        if self.pool.size >= self.max_serve_devices:
            policy.cancel(now)
            return
        if not self.supervisor.request_shrink():
            policy.cancel(now)  # training mesh already at one host
            return
        devices = jax.local_devices()
        device = devices[min(self.pool.size, len(devices) - 1)]
        cfg = self.cfg
        engine = EmbedEngine(
            self._model,
            self.reload.current_variables,
            max_batch=int(cfg.serve.max_batch),
            use_full_encoder=bool(cfg.parameter.use_full_encoder),
            metrics=self.metrics,
            warmup=True,
            device=device,
            replica_id=self.pool.size,
            weights=str(cfg.select("serve.weights", "exact")),
        )
        # bring it onto the serving generation under the swap lock (a swap
        # may have landed while the engine warmed)
        self.reload.resync_engine(engine)
        rep = self.pool.add_replica(engine)
        self.batcher.add_worker(rep)
        self.events.emit(
            "serve_scale", direction="grow", replicas=self.pool.size,
            replica=rep.rid,
        )
        logger.info(
            "queue pressure sustained: serve tier grown to %d replica(s); "
            "training mesh shrinking one host", self.pool.size,
        )

    def _shrink_serve(self, now: float, policy: ReallocationPolicy) -> None:
        """RELEASE: retire the lent replica, give the host back to training."""
        timeline = self.supervisor.hosts_timeline
        if not timeline or timeline[-1] >= self.nprocs:
            # The lent host is still draining out of the mesh: a generation
            # smaller than nprocs has not spawned yet. Releasing now would
            # make the host readmittable before the relaunch, so the remesh
            # would re-adopt it and training would never actually run on
            # the smaller mesh (and the grow-back path would never fire).
            # Stay lent; the policy retries after its cooldown.
            policy.cancel(now)
            return
        if self.pool.size > self.serve_devices:
            rid = max(r.rid for r in self.pool.replicas)
            self.batcher.retire_worker(rid)
            self.pool.remove_replica(rid)
            self.events.emit(
                "serve_scale", direction="shrink", replicas=self.pool.size,
                replica=rid,
            )
        released = self.supervisor.release_reallocation()
        logger.info(
            "pressure ebbed: serve tier back to %d replica(s); %d host(s) "
            "released to training", self.pool.size, released,
        )

    # -- run -----------------------------------------------------------------
    def run(self) -> dict:
        from simclr_tpu.obs.fleet import maybe_start_fleet
        from simclr_tpu.serve.server import shutdown_gracefully
        from simclr_tpu.supervisor.elastic import ElasticSupervisor
        from simclr_tpu.supervisor.runner import SupervisorKnobs

        cfg = self.cfg
        save_dir = resolve_save_dir(cfg)
        os.makedirs(save_dir, exist_ok=True)
        if not cfg.select("experiment.save_dir"):
            cfg.update_dotted("experiment.save_dir", save_dir, allow_new=True)
        if not cfg.select("serve.ready_file"):
            cfg.update_dotted(
                "serve.ready_file", os.path.join(save_dir, "serve.ready")
            )
        events_on = bool(cfg.select("telemetry.events", True))
        self.events = EventLog(save_dir, enabled=events_on)

        server_thread = self._build_serve_stack(save_dir)

        train_overrides = list(self.train_overrides)
        if not any(
            o.split("=", 1)[0].lstrip("+") == "experiment.save_dir"
            for o in train_overrides
        ):
            train_overrides.append(f"experiment.save_dir={save_dir}")
        fleet = maybe_start_fleet(cfg, save_dir, nprocs=self.nprocs)
        self.supervisor = ElasticSupervisor(
            [sys.executable, "-m", "simclr_tpu.main", *train_overrides],
            save_dir,
            SupervisorKnobs.from_config(cfg),
            nprocs=self.nprocs,
            devices_per_proc=self.devices_per_proc,
            global_batch=self.global_batch,
            grow_back_cooldown_s=float(
                cfg.select("supervisor.grow_back_cooldown_s", 60.0)
            ),
            force_cpu=self.force_cpu,
            coord_timeout_s=self.coord_timeout_s,
            events=EventLog(save_dir, enabled=events_on),
            fleet=fleet,
        )

        result_box: dict = {}

        def _train():
            try:
                result_box["result"] = self.supervisor.run()
            except BaseException as e:  # noqa: BLE001 - recorded in summary
                logger.exception("training supervisor died")
                result_box["error"] = f"{type(e).__name__}: {e}"

        train_thread = threading.Thread(
            target=_train, name="cosched-train", daemon=True
        )
        stop_reload = threading.Event()
        reload_thread = threading.Thread(
            target=self.reload.run,
            args=(stop_reload,),
            name="cosched-reload",
            daemon=True,
        )

        previous_handlers = {}
        if threading.current_thread() is threading.main_thread():
            # first signal drains training (guards checkpoint + exit 75 ->
            # clean supervisor exit); the serve tier then drains in the
            # ordinary teardown below
            def _on_stop(signum, frame):
                self.supervisor._on_stop(signum, frame)

            for sig in (signal.SIGTERM, signal.SIGINT):
                previous_handlers[sig] = signal.signal(sig, _on_stop)

        policy = ReallocationPolicy(
            high=float(cfg.select("cosched.pressure_high", 0.75)),
            low=float(cfg.select("cosched.pressure_low", 0.1)),
            sustain_s=float(cfg.select("cosched.pressure_sustain_s", 10.0)),
            cooldown_s=float(cfg.select("cosched.realloc_cooldown_s", 30.0)),
            enabled=bool(cfg.select("cosched.reallocation", True))
            and self.nprocs > 1,
        )
        queue_capacity = int(cfg.serve.queue_depth)
        train_thread.start()
        reload_thread.start()
        last_rejected = self.metrics.rejected_total.value
        try:
            while train_thread.is_alive():
                time.sleep(_POLICY_POLL_S)
                now = time.monotonic()
                rejected = self.metrics.rejected_total.value
                pressure = pressure_of(
                    int(self.metrics.queue_depth.value),
                    queue_capacity,
                    rejected - last_rejected,
                )
                last_rejected = rejected
                action = policy.observe(pressure, now)
                try:
                    if action == SHRINK:
                        self._grow_serve(now, policy)
                    elif action == RELEASE:
                        self._shrink_serve(now, policy)
                except Exception:  # pragma: no cover - policy must not
                    # take down a healthy train+serve system
                    logger.exception("reallocation move failed")
            train_thread.join()
        finally:
            for sig, handler in previous_handlers.items():
                signal.signal(sig, handler)
            stop_reload.set()
            reload_thread.join(timeout=60.0)
            shutdown_gracefully(self.server)
            self.server.server_close()
            server_thread.join(timeout=10.0)
            if fleet is not None:
                fleet.close()

        train_result = result_box.get("result") or {
            "outcome": "error",
            "exit": 1,
            "error": result_box.get("error", "supervisor thread died"),
        }
        summary = {
            "outcome": train_result.get("outcome"),
            "exit": int(train_result.get("exit", 1)),
            "swaps": self.reload.swap_count,
            "swap_rejected": self.reload.rejected_count,
            "reallocations": self.supervisor.reallocate_count,
            "serving_generation": self.pool.weights_generation,
            "serve_replicas": self.pool.size,
            "corpus_generation": getattr(
                getattr(self.server, "corpus_store", None), "generation", None
            ),
            "corpus_rows": getattr(
                getattr(self.server, "corpus_store", None), "rows", None
            ),
            "train": train_result,
        }
        atomic_write(
            os.path.join(save_dir, "cosched_summary.json"),
            lambda f: json.dump(summary, f, indent=2),
        )
        return summary
