"""Checkpoint watcher: zero-downtime generation swaps into the serve tier.

The hot-reload half of the co-scheduler. Training writes sha256-verified
epoch checkpoints into the run dir; this manager watches for them and
swaps each one into every serve replica without dropping, tearing, or
recompiling anything:

  1. **verify** — the sidecar digest must exist AND match. A checkpoint
     with no sidecar is an in-progress or legacy save (the sidecar is the
     commit signal) and is silently skipped until it appears; a digest
     mismatch (torn write, injected corruption — ``supervisor/faults.py``)
     rejects the swap: ``swap_rejected`` event +
     ``simclr_serve_swap_rejected_total``, prior generation keeps serving
     bitwise-unchanged, and the path is never retried.
  2. **stage** — pack the new variables device-side on EVERY replica
     (``EmbedEngine.stage_weights``): shape/dtype/structure-identical to
     the committed storage by contract, so the warm per-bucket jit cache
     serves the new weights with ZERO recompiles (an incompatible
     checkpoint raises and rejects the swap before any replica changes).
  3. **re-embed** — run the retrieval corpus through the STAGED weights on
     the primary replica (``embed_with`` — same compiled bucket programs,
     no serving metrics touched), so the fresh index exists before the
     swap is visible.
  4. **commit** — one atomic tuple assignment per replica; in-flight
     requests finish on the weights they already read, subsequent ones
     read generation N+1.
  5. **corpus swap** — publish a new generation-tagged
     :class:`~simclr_tpu.serve.retrieval.NeighborIndex` via
     ``EmbedServer.swap_index``, so ``/v1/neighbors`` answers from the
     same encoder generation as ``/v1/embed`` (both responses carry their
     generation headers; ``/healthz`` shows both numbers).

Any failure anywhere in 1-4 leaves every replica on the prior generation —
stage-all-then-commit-all means the pool can never serve a mixed or torn
weight set.
"""

from __future__ import annotations

import logging
import os
import threading
import time

import numpy as np

from simclr_tpu.utils.checkpoint import (
    CheckpointCorruptionError,
    epoch_of,
    list_checkpoints,
    verify_checkpoint,
)

logger = logging.getLogger("simclr_tpu.coscheduler")


def _default_load(path: str) -> dict:
    from simclr_tpu.eval import load_model_variables

    return load_model_variables(path)


class ReloadManager:
    """Watch ``save_dir`` for verified checkpoints; swap them into ``pool``.

    ``corpus_images`` (``(n, H, W, C)`` uint8, or None) is the retrieval
    corpus source: each committed generation re-embeds it and swaps the
    resulting index into ``server``. ``load_fn`` is injectable for tests
    (defaults to the blessed ``eval.load_model_variables`` restore path).
    """

    def __init__(
        self,
        pool,
        *,
        save_dir: str,
        server=None,
        events=None,
        metrics=None,
        corpus_images: np.ndarray | None = None,
        reembed_batch: int = 256,
        neighbors_metric: str = "dot",
        corpus_dtype: str = "fp32",
        ann_cells: int = 0,
        ann_probe: int = 1,
        poll_s: float = 2.0,
        load_fn=None,
    ):
        self.pool = pool
        self.save_dir = str(save_dir)
        self.server = server
        self.events = events
        self.metrics = metrics
        self.corpus_images = corpus_images
        self.reembed_batch = int(reembed_batch)
        self.neighbors_metric = neighbors_metric
        self.corpus_dtype = str(corpus_dtype)
        self.ann_cells = int(ann_cells)
        self.ann_probe = int(ann_probe)
        self.poll_s = float(poll_s)
        self._load = load_fn if load_fn is not None else _default_load
        # serialized swap/attach state: the policy thread resyncs freshly
        # grown replicas through the same lock the watcher swaps under, so
        # a replica can never join the pool on a half-superseded generation
        self.lock = threading.Lock()
        self.swapped_epoch = -1
        self.swap_count = 0
        self.rejected_count = 0
        self._rejected: set[str] = set()
        self._ckpt_mtime: float | None = None
        # host copy of the SERVING generation's variables — what a replica
        # grown by elastic reallocation boots from (None until first swap;
        # the core seeds it with the generation-0 init variables)
        self.current_variables: dict | None = None
        if metrics is not None:
            metrics.checkpoint_staleness_seconds.set_fn(self._staleness)

    # -- observability -------------------------------------------------------
    @property
    def generation(self) -> int:
        return self.pool.weights_generation

    def _staleness(self) -> float:
        """Seconds since the serving generation's checkpoint was written
        (0 until the first swap — generation 0 has no checkpoint)."""
        return time.time() - self._ckpt_mtime if self._ckpt_mtime else 0.0

    # -- corpus --------------------------------------------------------------
    def _reembed(self, engine, staged) -> np.ndarray:
        batch = max(1, min(self.reembed_batch, engine.max_batch))
        images = self.corpus_images
        return np.concatenate(
            [
                engine.embed_with(staged, images[i : i + batch])
                for i in range(0, images.shape[0], batch)
            ]
        )

    def _index_kwargs(self) -> dict:
        return {
            "metric": self.neighbors_metric,
            "corpus_dtype": self.corpus_dtype,
            "ann_cells": self.ann_cells,
            "ann_probe": self.ann_probe,
            "max_queries": self.pool.primary.max_batch,
            "sentry": self.pool.primary.sentry,
        }

    def _build_index(self, embeddings: np.ndarray, generation: int):
        from simclr_tpu.serve.retrieval import NeighborIndex

        return NeighborIndex(
            embeddings,
            metrics=self.metrics,
            generation=generation,
            **self._index_kwargs(),
        )

    def publish_index(self, embeddings: np.ndarray, generation: int) -> None:
        """Build + swap a generation-tagged index (also used by the core
        for the generation-0 corpus before traffic starts).

        Routes through the server's :class:`MutableCorpus` when one exists,
        so a per-swap re-embed and live ``/v1/corpus/*`` mutations share one
        generation sequence (the store keeps it monotone either way); the
        first publish creates the store and attaches it to the server.
        """
        if self.server is not None:
            from simclr_tpu.serve.retrieval import MutableCorpus

            store = getattr(self.server, "corpus_store", None)
            if store is None:
                store = MutableCorpus(
                    embeddings,
                    server=self.server,
                    metrics=self.metrics,
                    generation=generation,
                    **self._index_kwargs(),
                )
                self.server.corpus_store = store
            else:
                store.replace(embeddings, generation)
        elif self.metrics is not None:
            self.metrics.corpus_generation.set(generation)

    def bootstrap_corpus(self) -> None:
        """Embed + publish the startup corpus from the committed variables
        (a staged view of the weights already serving — no commit, no
        generation change), so ``/v1/neighbors`` works before the first
        checkpoint ever lands."""
        if self.corpus_images is None or self.current_variables is None:
            return
        with self.lock:
            engine = self.pool.primary
            staged = engine.stage_weights(self.current_variables)
            embeddings = self._reembed(engine, staged)
            self.publish_index(embeddings, self.pool.weights_generation)

    # -- swap protocol -------------------------------------------------------
    def poll_once(self) -> bool:
        """One watch pass; True if a new generation was committed."""
        candidates = [
            p
            for p in list_checkpoints(self.save_dir)
            if epoch_of(p) > self.swapped_epoch and p not in self._rejected
        ]
        for path in reversed(candidates):  # newest verified checkpoint wins
            try:
                verified = verify_checkpoint(path)
            except CheckpointCorruptionError as e:
                self._reject(path, f"digest mismatch: {e}")
                continue
            if not verified:
                # no sidecar: the save has not committed yet (or predates
                # integrity sidecars) — wait, don't reject
                continue
            return self.swap_to(path)
        return False

    def swap_to(self, path: str) -> bool:
        epoch = epoch_of(path)
        with self.lock:
            generation = self.pool.weights_generation + 1
            try:
                variables = self._load(path)
                replicas = list(self.pool.replicas)
                staged = [
                    rep.engine.stage_weights(variables, checkpoint_path=path)
                    for rep in replicas
                ]
                embeddings = (
                    self._reembed(replicas[0].engine, staged[0])
                    if self.corpus_images is not None
                    else None
                )
            except Exception as e:  # noqa: BLE001 - ANY failed swap must
                # leave the prior generation serving, not kill the watcher
                self._reject(path, f"{type(e).__name__}: {e}")
                return False
            for rep, st in zip(replicas, staged):
                rep.engine.commit(st, generation=generation)
            self.current_variables = variables
            if embeddings is not None:
                self.publish_index(embeddings, generation)
        self.swapped_epoch = epoch
        self.swap_count += 1
        try:
            self._ckpt_mtime = os.path.getmtime(path)
        except OSError:
            self._ckpt_mtime = time.time()
        if self.metrics is not None:
            self.metrics.weights_generation.set(generation)
            self.metrics.weight_swaps_total.inc()
        if self.events is not None:
            self.events.emit(
                "swap",
                epoch=epoch,
                generation=generation,
                path=path,
                replicas=len(self.pool.replicas),
            )
        logger.info(
            "hot-swapped epoch %d checkpoint as generation %d across %d "
            "replica(s)", epoch, generation, len(self.pool.replicas),
        )
        return True

    def _reject(self, path: str, reason: str) -> None:
        self._rejected.add(path)
        self.rejected_count += 1
        if self.metrics is not None:
            self.metrics.swap_rejected_total.inc()
        if self.events is not None:
            self.events.emit(
                "swap_rejected",
                epoch=epoch_of(path),
                path=path,
                reason=reason,
                serving_generation=self.pool.weights_generation,
            )
        logger.warning(
            "swap rejected for %s (%s); generation %d keeps serving",
            path, reason, self.pool.weights_generation,
        )

    # -- elastic grow support ------------------------------------------------
    def resync_engine(self, engine) -> None:
        """Bring a freshly built replica onto the SERVING generation before
        it joins the pool. Under the swap lock: stages the current host
        variables (if any swap has happened) and commits them with the
        pool's generation, so ``weights_generation`` (a min over replicas)
        never regresses when the tier grows."""
        with self.lock:
            generation = self.pool.weights_generation
            if self.current_variables is not None:
                staged = engine.stage_weights(self.current_variables)
                engine.commit(staged, generation=generation)
            else:
                engine.generation = generation

    # -- loop ----------------------------------------------------------------
    def run(self, stop: threading.Event) -> None:
        """Poll until ``stop`` is set, then one final pass so the terminal
        epoch's checkpoint (written just before training exits) still
        ships."""
        while not stop.is_set():
            try:
                self.poll_once()
            except Exception:  # pragma: no cover - watcher must survive
                logger.exception("checkpoint watch pass failed; retrying")
            stop.wait(self.poll_s)
        try:
            self.poll_once()
        except Exception:  # pragma: no cover
            logger.exception("final checkpoint watch pass failed")
