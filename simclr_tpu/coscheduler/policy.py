"""Elastic train/serve reallocation policy: pressure-driven hysteresis.

The decision half of the co-scheduler's device reallocation. The core
samples the serve tier's queue pressure (batcher depth as a fraction of
``serve.queue_depth``, saturated to 1.0 whenever requests were 429-shed
since the last sample) and feeds it here; this state machine decides WHEN
to lend a training host to the serve tier and when to give it back.
Deliberately pure and clock-injected (``observe(pressure, now)``) so the
policy is unit-testable without threads, sockets, or sleeps.

Two guards keep the split from flapping — the failure mode that would turn
elastic reallocation into a net loss (every direction change costs a
training drain + remesh):

  * **sustain**: pressure must stay past the threshold for
    ``pressure_sustain_s`` continuously; a single burst that drains on its
    own never moves devices. Samples inside the hysteresis band
    (``low < p < high``) reset both timers.
  * **cooldown**: ``realloc_cooldown_s`` must elapse between direction
    changes, bounding the worst-case remesh rate no matter how the load
    oscillates.
"""

from __future__ import annotations

SHRINK = "shrink"     # lend one training host to the serve tier
RELEASE = "release"   # give every lent host back to training


def pressure_of(queue_depth: int, queue_capacity: int, rejected_delta: int = 0) -> float:
    """Normalize the serve tier's load into [0, 1].

    Queue depth over capacity, saturated to 1.0 if ANY request was shed
    with 429 since the last sample — backpressure rejections mean the
    queue ceiling was hit between samples even if the depth looks low now.
    """
    if rejected_delta > 0:
        return 1.0
    if queue_capacity <= 0:
        return 0.0
    return min(1.0, max(0, queue_depth) / float(queue_capacity))


class ReallocationPolicy:
    """Two-state (idle | lent) hysteresis over a pressure signal.

    ``observe`` returns :data:`SHRINK` exactly once per idle->lent
    transition and :data:`RELEASE` once per lent->idle; the caller executes
    the move (or calls :meth:`cancel` if it could not).
    """

    def __init__(
        self,
        *,
        high: float = 0.75,
        low: float = 0.1,
        sustain_s: float = 10.0,
        cooldown_s: float = 30.0,
        enabled: bool = True,
    ):
        if not 0.0 <= low < high <= 1.0:
            raise ValueError(
                f"need 0 <= low < high <= 1, got low={low!r} high={high!r}"
            )
        self.high = float(high)
        self.low = float(low)
        self.sustain_s = float(sustain_s)
        self.cooldown_s = float(cooldown_s)
        self.enabled = bool(enabled)
        self.state = "idle"
        self._above_since: float | None = None
        self._below_since: float | None = None
        self._last_change: float | None = None

    def _cooled(self, now: float) -> bool:
        return (
            self._last_change is None
            or now - self._last_change >= self.cooldown_s
        )

    def observe(self, pressure: float, now: float) -> str | None:
        """Feed one pressure sample; returns SHRINK, RELEASE, or None."""
        if not self.enabled:
            return None
        if pressure >= self.high:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if (
                self.state == "idle"
                and now - self._above_since >= self.sustain_s
                and self._cooled(now)
            ):
                self.state = "lent"
                self._last_change = now
                self._above_since = None
                return SHRINK
        elif pressure <= self.low:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if (
                self.state == "lent"
                and now - self._below_since >= self.sustain_s
                and self._cooled(now)
            ):
                self.state = "idle"
                self._last_change = now
                self._below_since = None
                return RELEASE
        else:
            # hysteresis band: neither timer accumulates
            self._above_since = None
            self._below_since = None
        return None

    def cancel(self, now: float) -> None:
        """Undo the transition ``observe`` just returned because the move
        could not be executed (training mesh already at one host, serve
        tier at ``max_serve_devices``, ...). The cooldown clock still
        advances so a refused move is not retried every sample."""
        self.state = "idle" if self.state == "lent" else "lent"
        self._last_change = now
