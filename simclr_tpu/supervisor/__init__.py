"""Fault-tolerant run supervision (docs/FAULT_TOLERANCE.md).

Two halves: the in-process :class:`RunGuard` (preemption checkpointing,
heartbeat, non-finite-loss rollback — wired into ``main.py`` and
``supervised.py``) and the out-of-process supervisor runner
(``python -m simclr_tpu.supervisor`` — hang detection, backed-off restarts,
outcome classification).
"""

from simclr_tpu.supervisor.faults import FAULT_CRASH_CODE, FaultPlan
from simclr_tpu.supervisor.guard import (
    EXIT_POISONED,
    EXIT_PREEMPTED,
    PoisonedRun,
    PreemptedRun,
    RunGuard,
    nonfinite,
    preempt_checkpoint_name,
    resume_point,
)
from simclr_tpu.supervisor.heartbeat import (
    heartbeat_path,
    read_heartbeat,
    write_heartbeat,
)
from simclr_tpu.supervisor.runner import SupervisorKnobs, supervise

__all__ = [
    "FAULT_CRASH_CODE",
    "FaultPlan",
    "EXIT_POISONED",
    "EXIT_PREEMPTED",
    "PoisonedRun",
    "PreemptedRun",
    "RunGuard",
    "nonfinite",
    "preempt_checkpoint_name",
    "resume_point",
    "heartbeat_path",
    "read_heartbeat",
    "write_heartbeat",
    "SupervisorKnobs",
    "supervise",
]
