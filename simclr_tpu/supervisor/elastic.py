"""Elastic multi-host supervisor: per-host children, remesh-on-loss, grow-back.

``python -m simclr_tpu.supervisor.elastic --nprocs N --devices-per-proc D --
<entrypoint> <overrides…>`` runs one supervised training child PER HOST and
keeps the RUN alive across single-host failures, where the plain runner
(``runner.py``) wraps one process group and a single lost host kills the
world. The shape borrowed from MPMD worker-group recovery (PAPERS.md): lose
a host, keep the run.

A live ``jax.distributed`` process group cannot be resized, so elasticity is
group *generations*:

  1. launch one child per active host under a fresh rendezvous env
     (``parallel.multihost.group_env`` — new coordinator port, rewritten
     ``JAX_NUM_PROCESSES``, ranks reassigned over the active hosts);
  2. watch every child's exit code AND its per-host heartbeat
     (``heartbeat.json`` / ``heartbeat.p<i>.json``);
  3. on a single-host crash/wedge/preemption: emit ``host_lost``, tear the
     whole group down (the survivors are blocked in collectives — nothing
     gentler than SIGKILL reaches them), put the lost host on a cooldown,
     and relaunch on the survivors' smaller mesh — the child resumes from
     the latest sha256-verified checkpoint via the existing cross-topology
     restore, with ``experiment.batches`` rescaled so the GLOBAL batch (and
     with it steps/epoch and the per-step RNG schedule) is preserved;
  4. when the lost host's cooldown expires, drain the running group with
     SIGTERM (every guard checkpoints at the next epoch boundary and exits
     75) and relaunch at full topology — the grow-back.

Coordinator-aware backoff: each host carries its own consecutive-failure
counter, and its re-admission cooldown doubles from
``supervisor.grow_back_cooldown_s`` up to ``supervisor.backoff_max_s`` — a
flapping host burns its own cooldown, not the group's restart budget.

Every generation transition lands in the shared ``events.jsonl``
(``host_lost`` / ``remesh`` / ``grow_back``), and the summary written to
``supervisor_summary.json`` carries ``remesh_count``, ``grow_back_count``,
the ``hosts_timeline`` (e.g. ``[2, 1, 2]``) and a per-host table — the
post-mortem names which host died and when (``obs/report.py`` renders the
"hosts: 2→1→2" line from the remesh events).

Exit-code contract: same as the runner (0 clean / 75 preempted / 76
poisoned / last child code when the budget runs out).
"""

from __future__ import annotations

import json
import math
import os
import signal
import socket
import subprocess
import sys
import threading
import time

from simclr_tpu.obs.events import EventLog
from simclr_tpu.parallel.multihost import group_env
from simclr_tpu.supervisor.guard import EXIT_POISONED, EXIT_PREEMPTED
from simclr_tpu.supervisor.heartbeat import heartbeat_path, read_heartbeat
from simclr_tpu.supervisor.runner import (
    ENTRYPOINTS,
    ENV_ATTEMPT,
    OUTCOME_CLEAN,
    OUTCOME_CRASHED,
    OUTCOME_POISONED,
    OUTCOME_PREEMPTED,
    SupervisorKnobs,
    _BeatTracker,
    _write_summary,
    backoff_delay,
)

# the host's slot index within the FULL topology, exported to each child for
# log forensics (distinct from JAX_PROCESS_ID, which is the rank within the
# current — possibly shrunken — generation)
ENV_HOST_SLOT = "SIMCLR_ELASTIC_HOST_SLOT"


def free_port() -> int:
    """A fresh coordinator port per generation: the old group's coordinator
    socket may linger in TIME_WAIT, and a rebind race would hang the new
    rendezvous until the fail-fast timeout."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def rescaled_per_device_batch(
    global_batch: int, devices_per_host: int, n_hosts: int
) -> int:
    """Per-device batch that preserves ``global_batch`` on ``n_hosts`` hosts.

    The invariant elasticity must not break: global batch fixed means
    steps/epoch is fixed, means the per-step RNG schedule (which folds on
    the absolute step index) is the same trajectory the full mesh was
    walking. A topology whose device count does not divide the global batch
    is rejected loudly — silently rounding would fork the schedule.
    """
    n_devices = devices_per_host * n_hosts
    if n_devices <= 0 or global_batch % n_devices:
        raise ValueError(
            f"global batch {global_batch} is not divisible by "
            f"{n_devices} devices ({n_hosts} hosts x {devices_per_host}); "
            "this topology cannot preserve the global batch — pick a global "
            "batch divisible by every surviving-device count you expect"
        )
    return global_batch // n_devices


class _Host:
    """One host slot of the full topology: availability + its own
    consecutive-failure ledger (the coordinator-aware backoff)."""

    def __init__(self, slot: int):
        self.slot = slot
        self.lost = False
        self.reallocated = False
        self.failures = 0
        self.cooldown_until = 0.0
        self.loss_reasons: list[str] = []

    def mark_lost(self, reason: str, knobs: SupervisorKnobs, now: float) -> None:
        self.lost = True
        self.failures += 1
        self.loss_reasons.append(reason)
        cooldown = max(
            getattr(knobs, "grow_back_cooldown_s", 60.0),
            backoff_delay(knobs, self.failures - 1),
        )
        self.cooldown_until = now + min(cooldown, knobs.backoff_max_s)

    def mark_reallocated(self) -> None:
        """Lend this host to the serve tier: lost as far as the training
        mesh is concerned, but on an INFINITE cooldown — not a failure (no
        backoff ledger entry), and never readmittable until ``release``."""
        self.lost = True
        self.reallocated = True
        self.cooldown_until = math.inf

    def release(self, now: float) -> None:
        """Hand the host back to training: immediately readmittable, so the
        existing grow-back trigger fires on the next supervisor poll."""
        if self.reallocated:
            self.reallocated = False
            self.cooldown_until = now

    def readmittable(self, now: float) -> bool:
        return self.lost and now >= self.cooldown_until


class ElasticSupervisor:
    """Coordinator-side group supervisor; see module docstring.

    ``cmd_prefix`` is the child command WITHOUT the per-generation overrides
    (``[sys.executable, "-m", module, *overrides]``); each generation appends
    ``experiment.batches=<rescaled>`` plus ``resume_args`` after the first.
    """

    def __init__(
        self,
        cmd_prefix: list[str],
        save_dir: str,
        knobs: SupervisorKnobs,
        *,
        nprocs: int,
        devices_per_proc: int,
        global_batch: int,
        grow_back_cooldown_s: float = 60.0,
        resume_args: tuple[str, ...] = ("experiment.resume=true",),
        force_cpu: bool = False,
        coord_timeout_s: float | None = None,
        env: dict | None = None,
        events: EventLog | None = None,
        fleet=None,
    ):
        if nprocs < 1:
            raise ValueError(f"nprocs must be >= 1, got {nprocs}")
        self.cmd_prefix = list(cmd_prefix)
        self.save_dir = save_dir
        self.knobs = knobs
        # stash the elastic-only knob on the shared knobs object so
        # _Host.mark_lost sees one policy source
        self.knobs.grow_back_cooldown_s = float(grow_back_cooldown_s)
        self.nprocs = int(nprocs)
        self.devices_per_proc = int(devices_per_proc)
        self.global_batch = int(global_batch)
        self.resume_args = tuple(resume_args)
        self.force_cpu = bool(force_cpu)
        self.coord_timeout_s = coord_timeout_s
        self.base_env = dict(os.environ if env is None else env)
        self.events = events if events is not None else EventLog(
            save_dir, enabled=False
        )
        # optional obs.fleet.FleetCollector: scrapes every generation's
        # per-host exporters for the run's lifetime; its final snapshot is
        # embedded into the summary. The caller owns its lifecycle.
        self.fleet = fleet
        self.hosts = [_Host(i) for i in range(self.nprocs)]
        self.remesh_count = 0
        self.grow_back_count = 0
        self.reallocate_count = 0
        self.hosts_timeline: list[int] = []
        self._stop: dict[str, int | None] = {"sig": None}
        self._realloc: dict[str, bool] = {"shrink": False}
        self._children: list[subprocess.Popen] = []
        # validate the FULL topology up front: a bad global batch must fail
        # before any child is spawned, not at the first remesh
        rescaled_per_device_batch(
            self.global_batch, self.devices_per_proc, self.nprocs
        )

    # -- group lifecycle ----------------------------------------------------
    def _spawn_group(
        self, active: list[_Host], generation: int, resume: bool
    ) -> list[subprocess.Popen]:
        per_device = rescaled_per_device_batch(
            self.global_batch, self.devices_per_proc, len(active)
        )
        coordinator = f"127.0.0.1:{free_port()}"
        cmd = list(self.cmd_prefix) + [f"experiment.batches={per_device}"]
        if resume:
            cmd += list(self.resume_args)
        children = []
        for rank, host in enumerate(active):
            child_env = group_env(
                self.base_env,
                coordinator=coordinator,
                num_processes=len(active),
                process_id=rank,
                devices_per_proc=(
                    self.devices_per_proc if self.force_cpu else None
                ),
                coord_timeout_s=self.coord_timeout_s,
            )
            child_env[ENV_ATTEMPT] = str(generation)
            child_env[ENV_HOST_SLOT] = str(host.slot)
            if len(active) > 1 and "OMP_NUM_THREADS" not in child_env:
                child_env["OMP_NUM_THREADS"] = "1"
            children.append(subprocess.Popen(cmd, env=child_env))
        return children

    def _kill_group(self, sig: int = signal.SIGKILL) -> None:
        for proc in self._children:
            if proc.poll() is None:
                try:
                    proc.send_signal(sig)
                except OSError:
                    pass
        for proc in self._children:
            if proc.poll() is None:
                proc.wait()

    def _signal_group(self, sig: int) -> None:
        for proc in self._children:
            if proc.poll() is None:
                try:
                    proc.send_signal(sig)
                except OSError:
                    pass

    # -- elastic reallocation (coscheduler) ---------------------------------
    @property
    def active_host_count(self) -> int:
        return sum(1 for h in self.hosts if not h.lost)

    @property
    def reallocated_hosts(self) -> list[int]:
        return [h.slot for h in self.hosts if h.reallocated]

    def request_shrink(self) -> bool:
        """Ask the main loop to drain ONE host out of the training mesh and
        lend its devices to the serve tier. Serviced at the next supervisor
        poll: the group drains via SIGTERM (guards checkpoint at the epoch
        boundary and exit 75) and relaunches on the survivors through the
        ordinary remesh path. Returns False — request dropped — when the
        mesh is already at one host (a run always trains). Thread-safe:
        called from the coscheduler's pressure-policy thread.
        """
        if self.active_host_count <= 1:
            return False
        self._realloc["shrink"] = True
        return True

    def release_reallocation(self) -> int:
        """Hand every lent host back to training. The hosts become
        readmittable immediately, so the existing grow-back trigger drains
        the running group and remeshes back up at its next poll. Returns
        the number of hosts released."""
        now = time.monotonic()
        released = [h for h in self.hosts if h.reallocated]
        for h in released:
            h.release(now)
        if released:
            self.events.emit(
                "reallocate", direction="release",
                hosts=[h.slot for h in released],
            )
        return len(released)

    def _on_stop(self, signum, frame) -> None:
        escalate = self._stop["sig"] is not None
        self._stop["sig"] = signum
        # first request drains the group (guards checkpoint and exit 75);
        # repeats escalate to SIGKILL, same as the plain runner
        self._signal_group(signal.SIGKILL if escalate else signum)

    # -- wedge attribution --------------------------------------------------
    @staticmethod
    def _stalest_rank(trackers: dict[int, _BeatTracker]) -> int:
        """The rank whose beat went stale FIRST — the wedged host. The wedge
        fault fires before the beat write, so the culprit's last beat is one
        step older than its peers' (they beat once more, then block in the
        next collective). A rank with no beat at all is stalest of all."""
        def key(rank: int):
            tracker = trackers[rank]
            return (
                tracker.last_change is None,
                -(tracker.last_change or 0.0),
            )
        return max(trackers, key=key)

    # -- main loop ----------------------------------------------------------
    def run(self) -> dict:
        os.makedirs(self.save_dir, exist_ok=True)
        t0 = time.monotonic()
        poll_s = min(0.5, max(0.05, self.knobs.heartbeat_min_timeout_s / 4.0))
        generation = 0
        restarts = {"host_lost": 0, "grow_back": 0}
        last_rc: int | None = None

        previous_handlers = {}
        if threading.current_thread() is threading.main_thread():
            for sig in (signal.SIGTERM, signal.SIGINT):
                previous_handlers[sig] = signal.signal(sig, self._on_stop)

        def summary(outcome: str, exit_code: int, error: str | None = None):
            result = {
                "outcome": outcome,
                "exit": exit_code,
                "attempts": generation,
                "resumed": max(generation - 1, 0),
                "remesh_count": self.remesh_count,
                "grow_back_count": self.grow_back_count,
                "reallocate_count": self.reallocate_count,
                "hosts_timeline": list(self.hosts_timeline),
                "hosts": "→".join(str(n) for n in self.hosts_timeline),
                "host_table": {
                    str(h.slot): {
                        "losses": h.failures,
                        "reasons": list(h.loss_reasons),
                        "lost": h.lost,
                        "reallocated": h.reallocated,
                    }
                    for h in self.hosts
                },
                "restarts": dict(restarts),
                "final_child_exit": last_rc,
                "global_batch": self.global_batch,
                "save_dir": self.save_dir,
                "wall_time_s": round(time.monotonic() - t0, 3),
            }
            if error:
                result["error"] = error
            if self.fleet is not None:
                result["fleet"] = self.fleet.snapshot()
            self.events.emit(
                "outcome", outcome=outcome, exit=exit_code,
                attempt=generation, remesh_count=self.remesh_count,
                grow_back_count=self.grow_back_count,
            )
            _write_summary(self.save_dir, result)
            return result

        try:
            while True:
                now = time.monotonic()
                active = [h for h in self.hosts if not h.lost]
                if not active:
                    # every host is cooling down: wait for the earliest
                    # re-admission (interruptible by a stop request)
                    wake = min(h.cooldown_until for h in self.hosts)
                    while time.monotonic() < wake:
                        if self._stop["sig"] is not None:
                            return summary(OUTCOME_PREEMPTED, EXIT_PREEMPTED)
                        time.sleep(poll_s)
                    now = time.monotonic()
                for host in self.hosts:
                    if host.readmittable(now):
                        host.lost = False
                active = [h for h in self.hosts if not h.lost]

                generation += 1
                try:
                    self._children = self._spawn_group(
                        active, generation, resume=generation > 1
                    )
                except ValueError as exc:
                    # an indivisible surviving topology: reject loudly
                    return summary(OUTCOME_CRASHED, 1, error=str(exc))
                self.hosts_timeline.append(len(active))
                if generation > 1:
                    self.remesh_count += 1
                    self.events.emit(
                        "remesh",
                        attempt=generation,
                        hosts_before=self.hosts_timeline[-2],
                        hosts_after=len(active),
                        per_device_batch=rescaled_per_device_batch(
                            self.global_batch, self.devices_per_proc,
                            len(active),
                        ),
                        global_batch=self.global_batch,
                    )

                trackers = {
                    rank: _BeatTracker(
                        self.knobs,
                        read_heartbeat(heartbeat_path(self.save_dir, rank)),
                        time.monotonic(),
                    )
                    for rank in range(len(active))
                }
                drain_for_grow_back = False
                drain_for_realloc = False
                drain_deadline = None
                lost: tuple[_Host, str, int | None] | None = None

                while True:
                    exits = {
                        rank: proc.poll()
                        for rank, proc in enumerate(self._children)
                    }
                    if all(rc is not None for rc in exits.values()):
                        break
                    now = time.monotonic()
                    for rank, tracker in trackers.items():
                        tracker.observe(
                            read_heartbeat(
                                heartbeat_path(self.save_dir, rank)
                            ),
                            now,
                        )
                    if self._stop["sig"] is not None:
                        self._signal_group(signal.SIGTERM)
                        for proc in self._children:
                            proc.wait()
                        return summary(OUTCOME_PREEMPTED, EXIT_PREEMPTED)

                    finished = {
                        r: rc for r, rc in exits.items() if rc is not None
                    }
                    if finished and not (drain_for_grow_back or drain_for_realloc):
                        rank, rc = next(iter(finished.items()))
                        if len(finished) > 1:
                            # the faulted host's peers crash moments later
                            # (their collectives error out against the dead
                            # peer); the culprit is the one whose heartbeat
                            # went stale FIRST, same rule as the wedge path
                            rank = self._stalest_rank(
                                {r: trackers[r] for r in finished}
                            )
                            rc = finished[rank]
                        for r, code in finished.items():
                            if code == EXIT_POISONED:
                                rank, rc = r, code
                                break
                        last_rc = rc
                        if rc == EXIT_POISONED:
                            self._kill_group()
                            self.events.emit(
                                "child_exit", attempt=generation, exit=rc,
                                rank=rank, host=active[rank].slot,
                            )
                            return summary(OUTCOME_POISONED, EXIT_POISONED)
                        # a single child stopped while peers run: host loss
                        # (crash, injected die, or an externally preempted
                        # host exiting 75 on its own). The peers are blocked
                        # in a collective that will never complete.
                        reason = (
                            "preempted" if rc == EXIT_PREEMPTED else "crashed"
                        )
                        lost = (active[rank], reason, rc)
                        break
                    if (drain_for_grow_back or drain_for_realloc) and now > (
                        drain_deadline or 0
                    ):
                        # drain overran the deadline (a child stuck before
                        # its next boundary): force it — the relaunch resumes
                        # from the previous checkpoint either way
                        self._kill_group()
                        break
                    if not (drain_for_grow_back or drain_for_realloc):
                        hung = [
                            rank
                            for rank, tracker in trackers.items()
                            if tracker.timed_out(now)
                        ]
                        if hung:
                            culprit = self._stalest_rank(trackers)
                            lost = (active[culprit], "wedged", None)
                            break
                    if (
                        not (drain_for_grow_back or drain_for_realloc)
                        and self._realloc["shrink"]
                        and any(
                            t.last_change is not None
                            for t in trackers.values()
                        )
                    ):
                        # coscheduler asked for a host: drain the group at
                        # the next epoch boundary and relaunch one smaller.
                        # Deliberately the same remesh-on-loss machinery a
                        # real host loss takes — except the victim is parked
                        # (infinite cooldown), not penalized (no failure
                        # ledger entry, no restart-budget burn).
                        self._realloc["shrink"] = False
                        if len(active) > 1:
                            victim = active[-1]
                            victim.mark_reallocated()
                            drain_for_realloc = True
                            drain_deadline = now + self.knobs.startup_grace_s
                            self.reallocate_count += 1
                            self.events.emit(
                                "reallocate", direction="shrink",
                                attempt=generation, host=victim.slot,
                                hosts_before=len(active),
                                hosts_after=len(active) - 1,
                            )
                            self._signal_group(signal.SIGTERM)
                    if (
                        not (drain_for_grow_back or drain_for_realloc)
                        and len(active) < self.nprocs
                        and any(
                            h.readmittable(now) for h in self.hosts if h.lost
                        )
                        and any(
                            t.last_change is not None
                            for t in trackers.values()
                        )
                    ):
                        # a lost host is back and this generation has made
                        # progress: drain at the next epoch boundary and
                        # remesh back up
                        drain_for_grow_back = True
                        drain_deadline = now + self.knobs.startup_grace_s
                        self.grow_back_count += 1
                        restarts["grow_back"] += 1
                        returning = [
                            h.slot for h in self.hosts if h.readmittable(now)
                        ]
                        self.events.emit(
                            "grow_back", attempt=generation,
                            hosts=returning,
                            hosts_before=len(active),
                            hosts_after=len(active) + len(returning),
                        )
                        self._signal_group(signal.SIGTERM)
                    time.sleep(poll_s)

                if lost is not None:
                    host, reason, rc = lost
                    # the rest of the group is unrecoverable (blocked in
                    # collectives / half a mesh): tear it all down
                    self._kill_group()
                    now = time.monotonic()
                    host.mark_lost(reason, self.knobs, now)
                    self.events.emit(
                        "host_lost", attempt=generation, host=host.slot,
                        reason=reason, exit=rc,
                        cooldown_s=round(host.cooldown_until - now, 3),
                        failures=host.failures,
                    )
                    restarts["host_lost"] += 1
                    total_losses = sum(restarts.values()) - restarts["grow_back"]
                    if total_losses > self.knobs.max_restarts:
                        return summary(
                            OUTCOME_CRASHED,
                            rc if rc and 0 < rc < 256 else 1,
                            error=(
                                f"host-loss budget exhausted "
                                f"({self.knobs.max_restarts} restarts)"
                            ),
                        )
                    # brief group backoff before relaunching the survivors;
                    # the per-host cooldown (not this) is what throttles a
                    # flapping host
                    deadline = time.monotonic() + backoff_delay(
                        self.knobs, total_losses - 1
                    )
                    while time.monotonic() < deadline:
                        if self._stop["sig"] is not None:
                            return summary(OUTCOME_PREEMPTED, EXIT_PREEMPTED)
                        time.sleep(poll_s)
                    continue

                exits = [proc.returncode for proc in self._children]
                last_rc = exits[0] if exits else None
                if all(rc == 0 for rc in exits):
                    return summary(OUTCOME_CLEAN, 0)
                if drain_for_grow_back or drain_for_realloc:
                    # drained (75s, or forced): relaunch at the grown (or
                    # reallocation-shrunken) topology next iteration
                    continue
                if all(rc == EXIT_PREEMPTED for rc in exits):
                    # the whole group drained without a stop from us or a
                    # grow-back: an external whole-slice preemption
                    return summary(OUTCOME_PREEMPTED, EXIT_PREEMPTED)
                if any(rc == EXIT_POISONED for rc in exits):
                    return summary(OUTCOME_POISONED, EXIT_POISONED)
                # simultaneous multi-child crash: burn one restart and rerun
                # the same topology (no single host to blame)
                restarts["host_lost"] += 1
                total_losses = sum(restarts.values()) - restarts["grow_back"]
                bad = next(rc for rc in exits if rc != 0)
                last_rc = bad
                self.events.emit(
                    "child_exit", attempt=generation, exit=bad, group=True,
                )
                if total_losses > self.knobs.max_restarts:
                    return summary(
                        OUTCOME_CRASHED, bad if 0 < bad < 256 else 1
                    )
                deadline = time.monotonic() + backoff_delay(
                    self.knobs, total_losses - 1
                )
                while time.monotonic() < deadline:
                    if self._stop["sig"] is not None:
                        return summary(OUTCOME_PREEMPTED, EXIT_PREEMPTED)
                    time.sleep(poll_s)
        finally:
            self._kill_group()
            for sig, handler in previous_handlers.items():
                signal.signal(sig, handler)


def main(argv: list[str] | None = None) -> int:
    """CLI: ``python -m simclr_tpu.supervisor.elastic --nprocs N
    --devices-per-proc D [--force-cpu] -- <entrypoint> <overrides…>``."""
    import argparse

    from simclr_tpu.config import (
        ConfigError,
        check_supervisor_conf,
        check_telemetry_conf,
        load_config,
        resolve_save_dir,
    )

    parser = argparse.ArgumentParser(
        prog="python -m simclr_tpu.supervisor.elastic",
        description="Per-host elastic supervisor: remesh-on-loss + grow-back.",
    )
    parser.add_argument(
        "--nprocs", type=int, required=True,
        help="hosts (JAX processes) in the full topology",
    )
    parser.add_argument(
        "--devices-per-proc", type=int, required=True,
        help="accelerator devices per host (batch-rescale math)",
    )
    parser.add_argument(
        "--force-cpu", action="store_true",
        help="force that many VIRTUAL CPU devices per child (dryrun harness)",
    )
    parser.add_argument(
        "--coord-timeout-s", type=float, default=None,
        help="rendezvous fail-fast deadline exported to every child",
    )
    parser.add_argument("rest", nargs=argparse.REMAINDER)
    args = parser.parse_args(sys.argv[1:] if argv is None else argv)

    rest = list(args.rest)
    if rest and rest[0] == "--":
        rest = rest[1:]
    if not rest or rest[0] not in ENTRYPOINTS:
        known = ", ".join(sorted(set(ENTRYPOINTS)))
        print(
            "usage: python -m simclr_tpu.supervisor.elastic --nprocs N "
            "--devices-per-proc D -- <entrypoint> [overrides...]\n"
            f"  entrypoint: one of {known}",
            file=sys.stderr,
        )
        return 2
    module, config_name = ENTRYPOINTS[rest[0]]
    overrides = rest[1:]

    try:
        cfg = load_config(config_name, overrides=overrides)
        check_supervisor_conf(cfg)
        check_telemetry_conf(cfg)
        knobs = SupervisorKnobs.from_config(cfg)
        grow_back_cooldown_s = float(
            cfg.select("supervisor.grow_back_cooldown_s", 60.0)
        )
        save_dir = resolve_save_dir(cfg)
        per_device = int(cfg.select("experiment.batches", 0) or 0)
        if per_device <= 0:
            raise ConfigError(
                f"experiment.batches must be a positive per-device batch, "
                f"got {per_device!r}"
            )
    except ConfigError as e:
        print(f"elastic supervisor: {e}", file=sys.stderr)
        return 2
    if not cfg.select("experiment.save_dir"):
        overrides = overrides + [f"experiment.save_dir={save_dir}"]

    # experiment.batches carries PER-DEVICE semantics; the configured value
    # defines the run's invariant GLOBAL batch at full topology, and each
    # generation gets a rescaled per-device override appended (trailing
    # overrides win)
    global_batch = per_device * args.devices_per_proc * args.nprocs
    # fleet plane (telemetry.fleet=true): scrape every generation's per-host
    # exporters and serve the merged simclr_fleet_* endpoint for the run
    from simclr_tpu.obs.fleet import maybe_start_fleet

    fleet = maybe_start_fleet(cfg, save_dir, nprocs=args.nprocs)
    supervisor = ElasticSupervisor(
        [sys.executable, "-m", module, *overrides],
        save_dir,
        knobs,
        nprocs=args.nprocs,
        devices_per_proc=args.devices_per_proc,
        global_batch=global_batch,
        grow_back_cooldown_s=grow_back_cooldown_s,
        force_cpu=args.force_cpu,
        coord_timeout_s=args.coord_timeout_s,
        events=EventLog(
            save_dir, enabled=bool(cfg.select("telemetry.events", True))
        ),
        fleet=fleet,
    )
    try:
        result = supervisor.run()
    finally:
        if fleet is not None:
            fleet.close()
    print(json.dumps(result), flush=True)
    return int(result["exit"])


if __name__ == "__main__":
    sys.exit(main())
