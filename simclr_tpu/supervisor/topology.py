"""The run-dir topology sidecar: cross-topology resume accept/reject.

Elastic remeshing (``supervisor/elastic.py``) restarts a run on a DIFFERENT
device count than the one that wrote its checkpoints. The checkpoint layer
already handles the array mechanics (orbax restores into whatever shardings
the current mesh's restore template carries), but two run-level invariants
must be checked by the entry points themselves, and that needs a record of
the topology that wrote the run:

* the GLOBAL batch must be preserved — it fixes steps/epoch and with it the
  per-step RNG schedule (which folds on the absolute step index); a changed
  global batch silently forks the trajectory, so it is a hard error;
* a topology change is only coherent at an EPOCH boundary — a mid-epoch
  checkpoint's partial-epoch replay is defined in terms of the old per-device
  batch layout, so cross-topology + ``skip_steps > 0`` is rejected loudly.

``topology.json`` {n_devices, n_processes, global_batch} is written by the
logging host at every run start (after the resume check reads the PRIOR
generation's copy). Stdlib-only: callers pass the current topology in.
"""

from __future__ import annotations

import json
import os

TOPOLOGY_NAME = "topology.json"


def topology_path(save_dir: str) -> str:
    return os.path.join(save_dir, TOPOLOGY_NAME)


def read_topology(save_dir: str) -> dict | None:
    """The previous generation's topology record, or None (fresh run dir, or
    a run dir from before this sidecar existed — both resume unchecked, same
    as the historical behavior)."""
    try:
        with open(topology_path(save_dir), encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else None


def write_topology(
    save_dir: str, *, n_devices: int, n_processes: int, global_batch: int
) -> None:
    """Record the CURRENT topology (atomic: a crash mid-write must not leave
    a half sidecar to poison the next resume's check)."""
    from simclr_tpu.utils.ioutil import atomic_write

    os.makedirs(save_dir, exist_ok=True)
    payload = {
        "n_devices": int(n_devices),
        "n_processes": int(n_processes),
        "global_batch": int(global_batch),
    }
    atomic_write(
        topology_path(save_dir),
        lambda f: json.dump(payload, f, sort_keys=True),
    )


def check_resume_topology(
    prior: dict | None,
    *,
    n_devices: int,
    n_processes: int,
    global_batch: int,
    skip_steps: int,
) -> dict | None:
    """Accept or reject a resume onto the current topology.

    Returns None when the topology is unchanged (or no prior record exists),
    or a change dict ``{devices_before, devices_after, hosts_before,
    hosts_after, per_device_batch}`` when the device count changed and the
    resume is ACCEPTED — the caller logs it and emits a ``topology_change``
    event. Raises ``ValueError`` for the two rejections described in the
    module docstring.
    """
    if prior is None:
        return None
    try:
        prior_devices = int(prior.get("n_devices"))
        prior_processes = int(prior.get("n_processes", 1))
        prior_global = int(prior.get("global_batch"))
    except (TypeError, ValueError):
        return None  # unreadable sidecar: treat like a pre-sidecar run dir
    if prior_global != int(global_batch):
        raise ValueError(
            f"resume changes the GLOBAL batch ({prior_global} -> "
            f"{global_batch}); that forks steps/epoch and the per-step RNG "
            "schedule, so it cannot continue this run's trajectory. An "
            "elastic remesh must rescale experiment.batches so "
            "per_device x devices stays constant."
        )
    if prior_devices == int(n_devices):
        return None
    if int(skip_steps) > 0:
        raise ValueError(
            f"checkpoint is mid-epoch ({skip_steps} steps in) and the device "
            f"count changed ({prior_devices} -> {n_devices}); partial-epoch "
            "replay is defined in terms of the old per-device layout, so a "
            "cross-topology resume is only accepted at epoch boundaries — "
            "restart from the last epoch-boundary checkpoint"
        )
    return {
        "devices_before": prior_devices,
        "devices_after": int(n_devices),
        "hosts_before": prior_processes,
        "hosts_after": int(n_processes),
        "per_device_batch": int(global_batch) // max(int(n_devices), 1),
    }
