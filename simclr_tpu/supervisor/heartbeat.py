"""Heartbeat file — the liveness channel between a run and its supervisor.

Every training process rewrites one small JSON file at every host-loop step
boundary — ``heartbeat.json`` for process 0 (the path every pre-elastic
reader knows), ``heartbeat.p<i>.json`` for process ``i>0`` — and a
supervisor tails them to tell "slow" from "wedged" (``runner.py`` watches
process 0; the elastic supervisor watches one per host to attribute a wedge
to the host whose file went stale FIRST — the wedge fires before the beat
write, so the culprit's last beat is one step older than its peers', which
beat once more and then block in the next collective).
The write is an atomic rename so a reader never
sees a torn file, but deliberately does NOT fsync: a heartbeat is a liveness
signal, not a durable artifact — losing the last beat in a power cut is
indistinguishable from dying one step earlier, while an fsync per step would
put a disk flush on the training hot loop (``utils/ioutil.atomic_write``
keeps the fsync for artifacts a resume gate later trusts).

This module is stdlib-only: the supervisor runner imports it without paying
for (or risking any device touch through) jax.
"""

from __future__ import annotations

import json
import os
import tempfile
import time

HEARTBEAT_NAME = "heartbeat.json"

# status values a beat can carry; the supervisor only keys off file CHANGE
# (any rewrite proves the host loop is alive), status is for humans and tests
STATUS_RUNNING = "running"
STATUS_PREEMPTED = "preempted"


def heartbeat_path(save_dir: str, process_index: int = 0) -> str:
    """The run's heartbeat file, fixed relative to ``save_dir`` so the
    supervisor can find it without any channel to the child but argv.

    Process 0 keeps the historical ``heartbeat.json`` name (the runner, the
    report tool, and operators' ``watch cat`` all read it); process ``i>0``
    gets ``heartbeat.p<i>.json``, one liveness file per host."""
    if process_index:
        return os.path.join(save_dir, f"heartbeat.p{int(process_index)}.json")
    return os.path.join(save_dir, HEARTBEAT_NAME)


def write_heartbeat(
    path: str,
    *,
    step: int,
    epoch: int,
    loss: float | None = None,
    status: str = STATUS_RUNNING,
    telemetry: dict | None = None,
) -> None:
    """Atomically rewrite the heartbeat (rename, no fsync — see module doc).

    ``telemetry`` is the latest :meth:`Telemetry.snapshot` dict (host floats
    only); it rides on the beat so the supervisor — and anyone tailing the
    file — sees live throughput/MFU/loss without scraping the exporter.
    """
    payload = {
        "step": int(step),
        "epoch": int(epoch),
        "time": time.time(),
        "loss": None if loss is None else float(loss),
        "pid": os.getpid(),
        "status": status,
    }
    if telemetry is not None:
        payload["telemetry"] = telemetry
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".", prefix=os.path.basename(path) + ".tmp."
    )
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def read_heartbeat(path: str) -> dict | None:
    """Parse the heartbeat; ``None`` when absent or unreadable.

    A torn/garbage file is treated like no beat at all rather than an error:
    the supervisor's only decision is "has anything changed lately", and the
    atomic writer makes garbage transient.
    """
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else None
