"""In-process fault-tolerance guard for the training entry points.

Wired into ``main.py`` and ``supervised.py``, this gives every run three
reflexes the reference never had (save-only checkpoints, SURVEY §5.3-4):

  * **preemption**: SIGTERM/SIGINT set a flag; the host loop checks it at
    each step boundary, lands a checkpoint, and the entry point exits with
    the reserved "preempted, resumable" code 75 (EX_TEMPFAIL) — the contract
    the supervisor runner (and any outer orchestrator) keys restart-vs-crash off;
  * **heartbeat**: every process atomically rewrites its own
    ``<save_dir>/heartbeat[.p<i>].json`` each step so a supervisor can tell
    a slow step from a wedged one — and a per-host supervisor can tell
    WHICH host wedged;
  * **non-finite loss**: a NaN/Inf epoch loss rolls the run back to the
    newest sha256-verified checkpoint, with a bounded retry budget before
    the run is declared poisoned (exit 76).

The guard is constructed unconditionally — with no supervisor attached the
heartbeat is just a cheap status file and the signal handlers upgrade bare
``kill``/Ctrl-C into a clean resumable exit.
"""

from __future__ import annotations

import math
import signal
import threading

from simclr_tpu.supervisor.faults import FaultPlan
from simclr_tpu.supervisor.heartbeat import (
    STATUS_PREEMPTED,
    heartbeat_path,
    write_heartbeat,
)
from simclr_tpu.utils.logging import get_logger, is_logging_host

logger = get_logger()

# Exit-code contract (docs/FAULT_TOLERANCE.md). 75 is sysexits.h EX_TEMPFAIL
# ("temporary failure, user is invited to retry"); 76 (EX_PROTOCOL) is
# repurposed as "poisoned: retrying cannot help, do NOT auto-restart".
EXIT_PREEMPTED = 75
EXIT_POISONED = 76


class PreemptedRun(Exception):
    """Raised at a step boundary after the preemption checkpoint landed;
    entry points catch it in ``main()`` and exit :data:`EXIT_PREEMPTED`."""

    def __init__(self, checkpoint: str):
        super().__init__(f"preempted; resumable checkpoint at {checkpoint}")
        self.checkpoint = checkpoint


class PoisonedRun(Exception):
    """Raised when the NaN-rollback budget is exhausted (or no verified
    checkpoint exists to roll back to); entry points exit
    :data:`EXIT_POISONED` and the supervisor will NOT restart."""


def resume_point(step: int, steps_per_epoch: int) -> tuple[int, int]:
    """Map a restored step counter to ``(start_epoch, skip_steps)``.

    A boundary checkpoint resumes at the next epoch with nothing to skip; a
    mid-epoch (preemption) checkpoint replays its epoch's deterministic
    batch order, skipping the ``skip_steps`` batches already consumed — the
    per-step RNG folds on the absolute step index, so the continuation is
    exactly the run that would have happened without the preemption.
    """
    steps_per_epoch = max(steps_per_epoch, 1)
    return step // steps_per_epoch + 1, step % steps_per_epoch


class RunGuard:
    """One per run; see module docstring. Usage::

        guard = RunGuard(save_dir, nan_retry_budget=2)
        guard.install_signals()
        try:
            ... guard.beat(step, epoch, loss) each step ...
            ... if guard.preempt_requested: save + raise PreemptedRun ...
            ... loss = guard.checked_loss(step, loss); rollback on non-finite ...
        finally:
            guard.restore_signals()
    """

    def __init__(
        self,
        save_dir: str,
        *,
        nan_retry_budget: int = 2,
        telemetry=None,
        events=None,
        process_index: int = 0,
    ):
        self.save_dir = save_dir
        self.process_index = int(process_index)
        # every process beats into its OWN file (heartbeat.json for process
        # 0, heartbeat.p<i>.json beyond) so a per-host supervisor can
        # attribute a wedge to the host that stopped beating first
        self.heartbeat_file = heartbeat_path(save_dir, self.process_index)
        self.faults = FaultPlan(save_dir, process_index=self.process_index)
        self.nan_retry_budget = int(nan_retry_budget)
        self.nan_rollbacks = 0
        # optional observability attachments (simclr_tpu/obs/): a Telemetry
        # registry whose snapshot rides on every beat, and an EventLog for
        # the structured run timeline — duck-typed, no import needed
        self.telemetry = telemetry
        self.events = events
        self._preempt = threading.Event()
        self._previous_handlers: dict[int, object] = {}
        self._beats = True

    def _telemetry_snapshot(self) -> dict | None:
        return self.telemetry.snapshot() if self.telemetry is not None else None

    # -- signals ------------------------------------------------------------
    @property
    def preempt_requested(self) -> bool:
        return self._preempt.is_set()

    def _on_signal(self, signum, frame) -> None:
        # handler does the minimum: the host loop owns the checkpoint save
        # (a save from inside a handler could re-enter orbax mid-write)
        if not self._preempt.is_set():
            self._preempt.set()
            logger.info(
                "signal %d: checkpoint at the next step boundary, then exit %d",
                signum, EXIT_PREEMPTED,
            )

    def install_signals(self) -> None:
        """Claim SIGTERM/SIGINT; no-op off the main thread (in-process test
        drivers and notebook callers keep their own handlers)."""
        if threading.current_thread() is not threading.main_thread():
            return
        for sig in (signal.SIGTERM, signal.SIGINT):
            self._previous_handlers[sig] = signal.signal(sig, self._on_signal)

    def restore_signals(self) -> None:
        for sig, handler in self._previous_handlers.items():
            signal.signal(sig, handler)
        self._previous_handlers.clear()

    # -- heartbeat + fault hooks --------------------------------------------
    def beat(
        self,
        step: int,
        epoch: int,
        loss: float | None = None,
        status: str = "running",
    ) -> None:
        """Once per host-loop step (per epoch under ``epoch_compile`` — the
        scan is one indivisible program). Fires the die/wedge faults first:
        they must be able to kill the beat itself."""
        self.faults.maybe_die(step)
        self.faults.maybe_wedge(step)
        if self._beats:
            write_heartbeat(
                self.heartbeat_file, step=step, epoch=epoch, loss=loss,
                status=status, telemetry=self._telemetry_snapshot(),
            )

    def beat_preempted(self, step: int, epoch: int) -> None:
        """Final beat after the preemption checkpoint landed (forensics: the
        supervisor and operators see WHY the file stopped changing)."""
        if self._beats:
            write_heartbeat(
                self.heartbeat_file, step=step, epoch=epoch,
                status=STATUS_PREEMPTED, telemetry=self._telemetry_snapshot(),
            )

    def after_save(self, epoch: int, checkpoint_path: str) -> None:
        """Post-save hook: the corrupt-latest fault lives here (process 0
        only — it mutates the shared checkpoint files)."""
        if is_logging_host():
            self.faults.maybe_corrupt(epoch, checkpoint_path)

    # -- non-finite-loss guard ---------------------------------------------
    def checked_loss(self, step: int, loss: float) -> float:
        """The epoch-boundary loss, through the NaN fault hook."""
        return self.faults.maybe_nan(step, loss)

    def record_rollback(self, loss: float, restored: str | None) -> None:
        """Book one non-finite-loss rollback against the budget; raises
        :class:`PoisonedRun` when the budget is exhausted or there was no
        verified checkpoint to roll back to (``restored=None``)."""
        self.nan_rollbacks += 1
        if self.telemetry is not None:
            self.telemetry.record_nan_rollback()
        if self.events is not None:
            self.events.emit(
                "nan_rollback", loss=loss, checkpoint=restored,
                retry=self.nan_rollbacks, budget=self.nan_retry_budget,
            )
        if restored is None:
            raise PoisonedRun(
                f"loss {loss!r} is non-finite and no verified checkpoint "
                f"exists to roll back to: the run is poisoned"
            )
        if self.nan_rollbacks > self.nan_retry_budget:
            raise PoisonedRun(
                f"loss {loss!r} is non-finite and the rollback budget "
                f"(supervisor.nan_retry_budget={self.nan_retry_budget}) is "
                f"exhausted: the run is poisoned"
            )
        logger.warning(
            "non-finite loss %r: rolled back to %s (retry %d/%d)",
            loss, restored, self.nan_rollbacks, self.nan_retry_budget,
        )


def nonfinite(value: float) -> bool:
    return not math.isfinite(value)


def preempt_checkpoint_name(step: int, steps_per_epoch: int, stem: str) -> str:
    """Checkpoint directory name for a preemption save at ``step``.

    At an exact epoch boundary this IS the regular boundary checkpoint name
    (idempotent with a scheduled save of the same state). Mid-epoch it
    carries epoch = completed-epochs plus a ``-preempt`` tag;
    ``list_checkpoints`` orders the tagged variant after the plain boundary
    checkpoint of the same epoch — it holds strictly more steps.
    """
    from simclr_tpu.utils.checkpoint import checkpoint_name

    epochs_done, into_epoch = step // max(steps_per_epoch, 1), step % max(
        steps_per_epoch, 1
    )
    name = checkpoint_name(epochs_done, stem)
    if into_epoch:
        name += "-preempt"
    return name
