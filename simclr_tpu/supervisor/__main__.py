"""``python -m simclr_tpu.supervisor -- <entrypoint> <overrides…>``."""

import sys

from simclr_tpu.supervisor.runner import main

if __name__ == "__main__":
    sys.exit(main())
