"""Deterministic, env-gated fault injection for the fault-tolerance suite.

Every recovery path the supervisor promises (crash restart, hang kill, NaN
rollback, corrupt-checkpoint fallback) is only trustworthy if it can be
exercised on demand, on CPU, in CI. These hooks inject the faults at fixed
step/epoch boundaries so the e2e tests are reproducible:

  ``SIMCLR_FAULT_DIE_AT_STEP=K``       hard-exit (``os._exit``) once the host
                                       step counter reaches K — a crash with
                                       no cleanup, like a SIGKILL/OOM.
  ``SIMCLR_FAULT_WEDGE_AT_STEP=K``     stop beating and sleep forever at step
                                       K — a wedged device loop; only the
                                       supervisor's hang detection gets you out.
  ``SIMCLR_FAULT_NAN_AT_STEP=K``       report the first epoch-boundary loss at
                                       or after step K as NaN — drives the
                                       non-finite-loss rollback.
  ``SIMCLR_FAULT_CORRUPT_AT_EPOCH=E``  flip a byte in the epoch-E checkpoint
                                       right after it is saved (sidecar left
                                       stale) — the restore fallback path.
  ``SIMCLR_FAULT_DIE_PROCESS=P:K``     like DIE_AT_STEP=K, but fires only in
                                       the JAX process with index P — a
                                       single-host loss on a multi-host run
                                       (the elastic supervisor's remesh path).
  ``SIMCLR_FAULT_WEDGE_PROCESS=P:K``   like WEDGE_AT_STEP=K on process P only
                                       — a single wedged host; its peers keep
                                       beating for one more step then block
                                       in the next collective.

Each fault fires ONCE PER RUN DIRECTORY, recorded by a marker file in
``save_dir``: a supervisor restart re-executes the same env, and without the
marker the replayed child would die at the same step forever. The
process-scoped markers live in the same shared ``save_dir``, so a host that
returns after a remesh does not re-fire. Stdlib-only — the supervisor runner
and tests import this without jax; the caller passes ``process_index`` in
(``jax.process_index()`` from the entry points, 0 by default).
"""

from __future__ import annotations

import os
import time

ENV_DIE = "SIMCLR_FAULT_DIE_AT_STEP"
ENV_WEDGE = "SIMCLR_FAULT_WEDGE_AT_STEP"
ENV_NAN = "SIMCLR_FAULT_NAN_AT_STEP"
ENV_CORRUPT = "SIMCLR_FAULT_CORRUPT_AT_EPOCH"
ENV_DIE_PROCESS = "SIMCLR_FAULT_DIE_PROCESS"
ENV_WEDGE_PROCESS = "SIMCLR_FAULT_WEDGE_PROCESS"

# distinct from every meaningful code in the exit-code contract
# (docs/FAULT_TOLERANCE.md) so a fault-crash never masquerades as a
# preemption (75) or poisoning (76)
FAULT_CRASH_CODE = 13


def _env_int(name: str) -> int | None:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    return int(raw)


def _env_process_step(name: str) -> tuple[int, int] | None:
    """Parse a process-scoped ``P:K`` fault spec; None when unset. A
    malformed value raises immediately — a typo'd fault that silently never
    fires would green-light the very e2e it was meant to drive."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    process, sep, step = raw.partition(":")
    if not sep:
        raise ValueError(f"{name} must be 'PROCESS:STEP', got {raw!r}")
    return int(process), int(step)


class FaultPlan:
    """The armed faults for one run directory (all disarmed when the env is
    clean — the production case; every hook is then a no-op compare).

    ``process_index`` scopes the ``*_PROCESS=P:K`` faults: they arm only
    when it equals P. Passed in by the caller so this module stays
    stdlib-only (no ``jax.process_index()`` here)."""

    def __init__(self, save_dir: str, process_index: int = 0):
        self.save_dir = save_dir
        self.process_index = int(process_index)
        self.die_at_step = _env_int(ENV_DIE)
        self.wedge_at_step = _env_int(ENV_WEDGE)
        self.nan_at_step = _env_int(ENV_NAN)
        self.corrupt_at_epoch = _env_int(ENV_CORRUPT)
        for env, attr in (
            (ENV_DIE_PROCESS, "die_at_step"),
            (ENV_WEDGE_PROCESS, "wedge_at_step"),
        ):
            scoped = _env_process_step(env)
            if scoped is not None and scoped[0] == self.process_index:
                # fold into the same trigger the global fault uses (earliest
                # wins) so the hooks and markers below need no new paths —
                # the once-per-run-dir and FAULT_CRASH_CODE contracts hold
                current = getattr(self, attr)
                setattr(
                    self, attr,
                    scoped[1] if current is None else min(current, scoped[1]),
                )

    # -- once-per-run-dir markers ------------------------------------------
    def _marker(self, kind: str) -> str:
        return os.path.join(self.save_dir, f".fault_fired.{kind}")

    def _fired(self, kind: str) -> bool:
        return os.path.exists(self._marker(kind))

    def _fire(self, kind: str) -> None:
        os.makedirs(self.save_dir, exist_ok=True)
        with open(self._marker(kind), "w") as f:
            f.write(f"{time.time()}\n")

    # -- hooks --------------------------------------------------------------
    def maybe_die(self, step: int) -> None:
        if self.die_at_step is None or step < self.die_at_step or self._fired("die"):
            return
        self._fire("die")
        # _exit: no atexit, no finally, no orbax cleanup — a real hard crash
        os._exit(FAULT_CRASH_CODE)

    def maybe_wedge(self, step: int) -> None:
        if (
            self.wedge_at_step is None
            or step < self.wedge_at_step
            or self._fired("wedge")
        ):
            return
        self._fire("wedge")
        while True:  # beats stop; only SIGKILL ends this
            time.sleep(3600)

    def maybe_nan(self, step: int, loss: float) -> float:
        if self.nan_at_step is None or step < self.nan_at_step or self._fired("nan"):
            return loss
        self._fire("nan")
        return float("nan")

    def maybe_corrupt(self, epoch: int, checkpoint_path: str) -> None:
        if (
            self.corrupt_at_epoch is None
            or epoch < self.corrupt_at_epoch
            or self._fired("corrupt")
        ):
            return
        self._fire("corrupt")
        corrupt_checkpoint_bytes(checkpoint_path)


def corrupt_checkpoint_bytes(path: str) -> None:
    """Flip one byte mid-way through the checkpoint's largest file without
    touching the sha256 sidecar — exactly the bit-rot/truncation class the
    sidecar verification exists to catch."""
    files = [
        os.path.join(root, name)
        for root, _dirs, names in os.walk(path)
        for name in names
    ]
    files = [f for f in files if os.path.getsize(f) > 0]
    if not files:
        raise FileNotFoundError(f"no files to corrupt under {path!r}")
    victim = max(files, key=os.path.getsize)
    offset = os.path.getsize(victim) // 2
    with open(victim, "r+b") as f:
        f.seek(offset)
        byte = f.read(1)
        f.seek(offset)
        f.write(bytes([byte[0] ^ 0xFF]))
