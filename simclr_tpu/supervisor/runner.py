"""Supervisor runner: spawn, watch, restart.

``python -m simclr_tpu.supervisor -- <entrypoint> <overrides…>`` wraps a
training entry point the way Podracer-style fleets wrap their learners
(arXiv:2104.06272 §2: preemption is the normal case, restart-from-checkpoint
is the recovery): the entry point runs as a child process, the supervisor
tails its heartbeat file, and every way the child can stop — clean exit,
preemption (75), crash, poisoning (76), or a wedged loop that stops beating —
maps to either a backed-off restart (with ``experiment.resume=true`` forced)
or a terminal outcome in the supervisor's own exit code and one-line JSON
summary.

The supervisor itself never touches accelerators: the child owns the chips,
and a restart must start from a clean device state. Importing this module
pulls jax transitively (package ``__init__``), but no jax API is ever called
here — backend initialisation stays un-triggered in the supervisor process.

Exit-code contract (shared with ``guard.py``; docs/FAULT_TOLERANCE.md):
  0   clean — the run finished (possibly after restarts; see ``resumed``)
  75  preempted — stopped resumably (budget exhausted on preempts, or the
      supervisor itself was told to stop and drained the child)
  76  poisoned — the child declared retrying useless; NOT restarted
  else  crashed — the child's last exit code, after the retry budget
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

from simclr_tpu.obs.events import EventLog, events_path, read_events
from simclr_tpu.supervisor.guard import EXIT_POISONED, EXIT_PREEMPTED
from simclr_tpu.supervisor.heartbeat import heartbeat_path, read_heartbeat

OUTCOME_CLEAN = "clean"
OUTCOME_PREEMPTED = "preempted"
OUTCOME_CRASHED = "crashed"
OUTCOME_POISONED = "poisoned"

# the attempt ordinal, exported to the child for log-line tagging
ENV_ATTEMPT = "SIMCLR_SUPERVISOR_ATTEMPT"

SUMMARY_NAME = "supervisor_summary.json"

# entrypoint alias -> (python -m module, root config name for knob/save_dir
# resolution). The supervisor composes the SAME config the child will, so
# supervisor.* overrides and experiment.save_dir resolve identically.
ENTRYPOINTS = {
    "pretrain": ("simclr_tpu.main", "config"),
    "main": ("simclr_tpu.main", "config"),
    "simclr_tpu.main": ("simclr_tpu.main", "config"),
    "supervised": ("simclr_tpu.supervised", "supervised_config"),
    "simclr_tpu.supervised": ("simclr_tpu.supervised", "supervised_config"),
}


@dataclasses.dataclass
class SupervisorKnobs:
    """Restart/backoff/hang-detection policy (``supervisor.*`` config keys,
    validated by ``config.check_supervisor_conf``)."""

    max_restarts: int = 8
    backoff_base_s: float = 5.0
    backoff_max_s: float = 300.0
    heartbeat_timeout_factor: float = 10.0
    heartbeat_min_timeout_s: float = 30.0
    startup_grace_s: float = 600.0

    @classmethod
    def from_config(cls, cfg) -> "SupervisorKnobs":
        d = cls()
        return cls(
            max_restarts=int(cfg.select("supervisor.max_restarts", d.max_restarts)),
            backoff_base_s=float(
                cfg.select("supervisor.backoff_base_s", d.backoff_base_s)
            ),
            backoff_max_s=float(
                cfg.select("supervisor.backoff_max_s", d.backoff_max_s)
            ),
            heartbeat_timeout_factor=float(
                cfg.select(
                    "supervisor.heartbeat_timeout_factor", d.heartbeat_timeout_factor
                )
            ),
            heartbeat_min_timeout_s=float(
                cfg.select(
                    "supervisor.heartbeat_min_timeout_s", d.heartbeat_min_timeout_s
                )
            ),
            startup_grace_s=float(
                cfg.select("supervisor.startup_grace_s", d.startup_grace_s)
            ),
        )


def backoff_delay(knobs: SupervisorKnobs, prior_restarts: int) -> float:
    """Exponential restart delay, capped at ``supervisor.backoff_max_s``.

    Uncapped doubling from ``backoff_base_s`` reaches hours by restart 12
    and days by 15 — a run with a generous budget would spend its life
    sleeping. Shared with the elastic supervisor's per-host re-admission
    cooldown so both policies cap identically."""
    return min(
        knobs.backoff_base_s * (2.0 ** prior_restarts), knobs.backoff_max_s
    )


class _BeatTracker:
    """Distinguishes slow from wedged for ONE child attempt.

    Any rewrite of the heartbeat file counts as a beat (the payload carries a
    wall-time field, so every write changes the fingerprint). The allowed gap
    adapts to the observed cadence: an EWMA of inter-beat intervals times
    ``heartbeat_timeout_factor``, floored by ``heartbeat_min_timeout_s`` so a
    fast loop's jitter can't trip it. Before the first NEW beat (a stale file
    from the previous attempt does not count) the child gets
    ``startup_grace_s`` — the compile window on real runs.
    """

    _EWMA_ALPHA = 0.3

    def __init__(self, knobs: SupervisorKnobs, baseline: dict | None, now: float):
        self.knobs = knobs
        self.started = now
        self.last_change: float | None = None
        self.ewma: float | None = None
        self._fingerprint = self._fp(baseline)

    @staticmethod
    def _fp(payload: dict | None):
        if payload is None:
            return None
        return (payload.get("pid"), payload.get("step"), payload.get("time"))

    def observe(self, payload: dict | None, now: float) -> None:
        fp = self._fp(payload)
        if payload is None or fp == self._fingerprint:
            return
        if self.last_change is not None:
            interval = now - self.last_change
            self.ewma = (
                interval
                if self.ewma is None
                else (1 - self._EWMA_ALPHA) * self.ewma + self._EWMA_ALPHA * interval
            )
        self._fingerprint = fp
        self.last_change = now

    def timed_out(self, now: float) -> bool:
        if self.last_change is None:
            return now - self.started > self.knobs.startup_grace_s
        limit = self.knobs.heartbeat_min_timeout_s
        if self.ewma is not None:
            limit = max(limit, self.knobs.heartbeat_timeout_factor * self.ewma)
        return now - self.last_change > limit


def _write_summary(save_dir: str, summary: dict) -> None:
    path = os.path.join(save_dir, SUMMARY_NAME)
    fd, tmp = tempfile.mkstemp(dir=save_dir, prefix=SUMMARY_NAME + ".tmp.")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(summary, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def supervise(
    cmd: list[str],
    save_dir: str,
    knobs: SupervisorKnobs,
    *,
    resume_args: tuple[str, ...] | list[str] = (),
    env: dict | None = None,
    events: EventLog | None = None,
    fleet=None,
) -> dict:
    """Run ``cmd`` under supervision until a terminal outcome; returns the
    summary dict (also written to ``<save_dir>/supervisor_summary.json``).

    ``resume_args`` are appended to the command on every attempt AFTER the
    first — the entry points apply overrides in order, so a trailing
    ``experiment.resume=true`` wins whatever the caller passed.

    ``events`` (an :class:`~simclr_tpu.obs.events.EventLog` on the SAME
    ``save_dir``) records the supervisor side of the run timeline —
    child exits, hangs, backed-off restarts, the terminal outcome — into
    the child's own ``events.jsonl``, each stamped with the attempt it
    describes.

    ``fleet`` (a running :class:`~simclr_tpu.obs.fleet.FleetCollector`, or
    None) scrapes the child's per-host exporters for the run's lifetime;
    its final snapshot is embedded into the summary under ``"fleet"``. The
    caller owns its lifecycle (``main()`` starts and closes it).
    """
    os.makedirs(save_dir, exist_ok=True)
    hb_path = heartbeat_path(save_dir)
    if events is None:
        events = EventLog(save_dir, enabled=False)
    # poll fast enough to resolve the configured minimum timeout
    poll_s = min(0.5, max(0.05, knobs.heartbeat_min_timeout_s / 4.0))

    restarts = {"preempted": 0, "crashed": 0, "hung": 0}
    stop_signal: dict[str, int | None] = {"sig": None}
    child: dict[str, subprocess.Popen | None] = {"proc": None}

    def _forward_stop(signum, frame):
        # first stop request: drain the child (its guard checkpoints and
        # exits 75); repeated requests escalate to SIGKILL
        proc = child["proc"]
        escalate = stop_signal["sig"] is not None
        stop_signal["sig"] = signum
        if proc is not None and proc.poll() is None:
            proc.kill() if escalate else proc.send_signal(signum)

    previous_handlers = {}
    if threading.current_thread() is threading.main_thread():
        for sig in (signal.SIGTERM, signal.SIGINT):
            previous_handlers[sig] = signal.signal(sig, _forward_stop)

    t0 = time.monotonic()
    attempt = 0
    last_rc: int | None = None

    def _summary(outcome: str, exit_code: int) -> dict:
        summary = {
            "outcome": outcome,
            "exit": exit_code,
            "attempts": attempt,
            "resumed": attempt - 1,
            "restarts": dict(restarts),
            "final_child_exit": last_rc,
            "save_dir": save_dir,
            "wall_time_s": round(time.monotonic() - t0, 3),
        }
        # surface the child's last telemetry snapshot (riding on its final
        # heartbeat) so one file answers "how fast was it going when it ended"
        beat = read_heartbeat(hb_path)
        if beat is not None and isinstance(beat.get("telemetry"), dict):
            summary["telemetry"] = beat["telemetry"]
        # anomaly forensics come from the shared events.jsonl timeline, NOT
        # the heartbeat snapshot: a wedged child's final heartbeat predates
        # its stall (the wedge fires before the beat is written), so only
        # the detector's events carry the truth
        counts = {"slow_steps": 0, "stalls": 0, "auto_traces": 0}
        for event in read_events(events_path(save_dir)):
            kind = event.get("event")
            if kind == "slow_step":
                counts["slow_steps"] += 1
            elif kind == "stall":
                counts["stalls"] += 1
            elif kind == "auto_trace":
                counts["auto_traces"] += 1
        summary["anomalies"] = counts
        if fleet is not None:
            # the fleet plane's last word: per-host up/staleness, step-time
            # skew, slowest host — the post-mortem's multi-host view
            summary["fleet"] = fleet.snapshot()
        events.emit(
            "outcome", outcome=outcome, exit=exit_code, attempt=attempt,
            resumed=attempt - 1,
        )
        _write_summary(save_dir, summary)
        return summary

    try:
        while True:
            attempt += 1
            full_cmd = list(cmd) + (list(resume_args) if attempt > 1 else [])
            child_env = dict(os.environ if env is None else env)
            child_env[ENV_ATTEMPT] = str(attempt)
            tracker = _BeatTracker(knobs, read_heartbeat(hb_path), time.monotonic())
            proc = subprocess.Popen(full_cmd, env=child_env)
            child["proc"] = proc
            hung = False
            while True:
                try:
                    rc = proc.wait(timeout=poll_s)
                    break
                except subprocess.TimeoutExpired:
                    pass
                now = time.monotonic()
                tracker.observe(read_heartbeat(hb_path), now)
                if stop_signal["sig"] is None and tracker.timed_out(now):
                    # wedged: no beat within the adaptive window. SIGKILL —
                    # a hung SPMD program won't honor anything gentler
                    hung = True
                    events.emit("hang", attempt=attempt)
                    proc.kill()
                    rc = proc.wait()
                    break
            child["proc"] = None
            last_rc = rc
            events.emit("child_exit", attempt=attempt, exit=rc, hung=hung)

            if not hung and rc == 0:
                return _summary(OUTCOME_CLEAN, 0)
            if not hung and rc == EXIT_POISONED:
                # retrying cannot help (NaN budget exhausted / no verified
                # checkpoint): restarting would loop the same failure
                return _summary(OUTCOME_POISONED, EXIT_POISONED)
            if stop_signal["sig"] is not None:
                # the stop was ours (forwarded); never count it as a crash
                return _summary(OUTCOME_PREEMPTED, EXIT_PREEMPTED)

            kind = (
                "hung" if hung else "preempted" if rc == EXIT_PREEMPTED else "crashed"
            )
            total = sum(restarts.values())
            if total >= knobs.max_restarts:
                if kind == "preempted":
                    return _summary(OUTCOME_PREEMPTED, EXIT_PREEMPTED)
                exit_code = rc if 0 < rc < 256 else 1
                return _summary(OUTCOME_CRASHED, exit_code)
            restarts[kind] += 1
            backoff = backoff_delay(knobs, total)
            events.emit(
                "restart", attempt=attempt, kind=kind, exit=rc,
                backoff_s=backoff, restart=total + 1,
                max_restarts=knobs.max_restarts,
            )
            print(
                f"supervisor: child {kind} (exit {rc}); restart "
                f"{total + 1}/{knobs.max_restarts} in {backoff:.1f}s",
                file=sys.stderr,
                flush=True,
            )
            deadline = time.monotonic() + backoff
            while time.monotonic() < deadline:
                if stop_signal["sig"] is not None:
                    return _summary(OUTCOME_PREEMPTED, EXIT_PREEMPTED)
                time.sleep(min(poll_s, max(deadline - time.monotonic(), 0.0)))
    finally:
        proc = child["proc"]
        if proc is not None and proc.poll() is None:
            proc.kill()
        for sig, handler in previous_handlers.items():
            signal.signal(sig, handler)


def main(argv: list[str] | None = None) -> int:
    """CLI: ``python -m simclr_tpu.supervisor -- <entrypoint> <overrides…>``."""
    from simclr_tpu.config import (
        ConfigError,
        check_supervisor_conf,
        check_telemetry_conf,
        load_config,
        resolve_save_dir,
    )

    args = list(sys.argv[1:] if argv is None else argv)
    if args and args[0] == "--":
        args = args[1:]
    if not args or args[0] not in ENTRYPOINTS:
        known = ", ".join(sorted(set(ENTRYPOINTS)))
        print(
            "usage: python -m simclr_tpu.supervisor -- <entrypoint> [overrides...]\n"
            f"  entrypoint: one of {known}",
            file=sys.stderr,
        )
        return 2
    module, config_name = ENTRYPOINTS[args[0]]
    overrides = args[1:]
    if any(a in ("--multirun", "-m") for a in overrides):
        print(
            "supervisor: --multirun is not supported (one supervisor per run; "
            "wrap each sweep job separately)",
            file=sys.stderr,
        )
        return 2

    try:
        cfg = load_config(config_name, overrides=overrides)
        check_supervisor_conf(cfg)
        check_telemetry_conf(cfg)
        knobs = SupervisorKnobs.from_config(cfg)
        save_dir = resolve_save_dir(cfg)
    except ConfigError as e:
        print(f"supervisor: {e}", file=sys.stderr)
        return 2
    if not cfg.select("experiment.save_dir"):
        # pin the resolved (timestamped) run dir: every restart must land in
        # the SAME directory or resume would never find the checkpoints
        overrides = overrides + [f"experiment.save_dir={save_dir}"]

    cmd = [sys.executable, "-m", module, *overrides]
    # fleet plane (telemetry.fleet=true): scrape the child's per-host
    # exporters and serve the merged simclr_fleet_* endpoint for the run
    from simclr_tpu.obs.fleet import maybe_start_fleet

    fleet = maybe_start_fleet(cfg, save_dir)
    try:
        summary = supervise(
            cmd, save_dir, knobs, resume_args=("experiment.resume=true",),
            events=EventLog(
                save_dir, enabled=bool(cfg.select("telemetry.events", True))
            ),
            fleet=fleet,
        )
    finally:
        if fleet is not None:
            fleet.close()
    print(json.dumps(summary), flush=True)
    return int(summary["exit"])
