"""Serving metrics: the serve tier's metric set over the shared primitives.

The dependency-free Counter/Gauge/Summary primitives were promoted to
:mod:`simclr_tpu.obs.metrics` so the training-side telemetry registry
(``obs/telemetry.py``) shares one rendering implementation; they are
re-exported here unchanged — existing ``from simclr_tpu.serve.metrics
import Counter`` imports and the serve ``/metrics`` endpoint render
byte-identically (locked by ``tests/test_obs.py``).
"""

from __future__ import annotations

from simclr_tpu.obs.metrics import Counter, Gauge, Histogram, Summary

__all__ = ["Counter", "Gauge", "Histogram", "ServeMetrics", "Summary"]


class ServeMetrics:
    """The serving stack's metric set, shared by engine, batcher, server.

    Naming follows Prometheus conventions (``_total`` counters, explicit
    units in names). ``avg_batch_fill()`` — requests coalesced per engine
    batch — is the dynamic-batching health number: 1.0 means no coalescing
    is happening (either no concurrency or ``max_delay_ms`` too low).
    """

    def __init__(self):
        self.requests_total = Counter(
            "simclr_serve_requests_total", "Embed requests accepted into the queue")
        self.rows_total = Counter(
            "simclr_serve_rows_total", "Image rows accepted into the queue")
        self.rejected_total = Counter(
            "simclr_serve_rejected_total",
            "Embed requests rejected with backpressure (queue full)")
        self.failed_total = Counter(
            "simclr_serve_failed_total", "Embed requests that failed in the engine")
        self.batches_total = Counter(
            "simclr_serve_batches_total", "Engine batches dispatched")
        self.batch_requests_total = Counter(
            "simclr_serve_batch_requests_total",
            "Requests coalesced into dispatched batches")
        self.batch_rows_total = Counter(
            "simclr_serve_batch_rows_total", "Rows across dispatched batches")
        self.batch_capacity_total = Counter(
            "simclr_serve_batch_capacity_total",
            "Padded bucket capacity across dispatched batches (rows)")
        self.compile_cache_hits_total = Counter(
            "simclr_serve_compile_cache_hits_total",
            "Engine batches whose bucket was already warm (no compile)")
        self.compile_cache_misses_total = Counter(
            "simclr_serve_compile_cache_misses_total",
            "Engine batches that compiled a cold bucket")
        self.recompile_alarms_total = Counter(
            "simclr_serve_recompile_alarms_total",
            "Buckets compiled after warmup completed — live traffic paid a compile")
        self.queue_depth = Gauge(
            "simclr_serve_queue_depth", "Requests waiting in the batcher queue")
        self.request_latency_ms = Summary(
            "simclr_serve_request_latency_ms",
            "Submit-to-result latency per request (milliseconds)")
        self.batch_latency_ms = Summary(
            "simclr_serve_batch_latency_ms",
            "Engine forward latency per dispatched batch (milliseconds)")
        self.client_disconnects_total = Counter(
            "simclr_serve_client_disconnects_total",
            "Responses dropped mid-write by a disconnecting client")
        self.neighbors_requests_total = Counter(
            "simclr_serve_neighbors_requests_total",
            "Neighbor-search requests answered")
        self.neighbors_queries_total = Counter(
            "simclr_serve_neighbors_queries_total",
            "Query rows across neighbor-search requests")
        self.neighbors_latency_ms = Summary(
            "simclr_serve_neighbors_latency_ms",
            "On-device top-k latency per neighbors request (milliseconds)")
        self.corpus_hbm_bytes = Gauge(
            "simclr_serve_corpus_hbm_bytes",
            "Row-sharded retrieval corpus bytes resident in device HBM")
        self.corpus_rows = Gauge(
            "simclr_serve_corpus_rows",
            "Embedding rows in the resident retrieval corpus")
        self.ann_cells_probed = Gauge(
            "simclr_serve_ann_cells_probed",
            "IVF cells scored per query per shard (0 = exact scan)")
        # continuous-reload plane (coscheduler): generation/staleness of the
        # weights the pool is serving, plus the swap outcome counters the
        # chaos tests pin (a rejected swap must bump swap_rejected_total and
        # NOTHING else)
        self.weights_generation = Gauge(
            "simclr_serve_weights_generation",
            "Checkpoint generation the replica pool is serving (0 = startup weights)")
        self.corpus_generation = Gauge(
            "simclr_serve_corpus_generation",
            "Encoder generation that embedded the resident retrieval corpus")
        self.checkpoint_staleness_seconds = Gauge(
            "simclr_serve_checkpoint_staleness_seconds",
            "Seconds since the serving generation's checkpoint was written")
        self.weight_swaps_total = Counter(
            "simclr_serve_weight_swaps_total",
            "Zero-downtime weight generation swaps committed to every replica")
        self.swap_rejected_total = Counter(
            "simclr_serve_swap_rejected_total",
            "Checkpoint swaps refused (corrupt/unverified/incompatible); prior generation kept")
        # ReplicaPool for the {replica="N"}-labeled per-replica gauges;
        # attached by start_server when serving through a pool
        self._pool = None

    def attach_pool(self, pool) -> None:
        self._pool = pool

    def avg_batch_fill(self) -> float:
        """Mean requests coalesced per dispatched engine batch."""
        batches = self.batches_total.value
        return self.batch_requests_total.value / batches if batches else 0.0

    def fill_ratio(self) -> float:
        """Mean real-rows / padded-bucket-capacity across batches."""
        capacity = self.batch_capacity_total.value
        return self.batch_rows_total.value / capacity if capacity else 0.0

    def render(self) -> str:
        parts = [
            m.render()
            for m in (
                self.requests_total, self.rows_total, self.rejected_total,
                self.failed_total, self.batches_total,
                self.batch_requests_total, self.batch_rows_total,
                self.batch_capacity_total, self.compile_cache_hits_total,
                self.compile_cache_misses_total, self.recompile_alarms_total,
                self.queue_depth,
                self.request_latency_ms, self.batch_latency_ms,
                self.client_disconnects_total,
                self.neighbors_requests_total, self.neighbors_queries_total,
                self.neighbors_latency_ms, self.corpus_hbm_bytes,
                self.corpus_rows, self.ann_cells_probed,
                self.weights_generation, self.corpus_generation,
                self.checkpoint_staleness_seconds,
                self.weight_swaps_total, self.swap_rejected_total,
            )
        ]
        parts.append(
            "# HELP simclr_serve_avg_batch_fill Mean requests per dispatched batch\n"
            "# TYPE simclr_serve_avg_batch_fill gauge\n"
            f"simclr_serve_avg_batch_fill {self.avg_batch_fill():g}\n"
        )
        parts.append(
            "# HELP simclr_serve_batch_fill_ratio Mean rows over padded bucket capacity\n"
            "# TYPE simclr_serve_batch_fill_ratio gauge\n"
            f"simclr_serve_batch_fill_ratio {self.fill_ratio():g}\n"
        )
        if self._pool is not None:
            parts.append(self._render_replicas())
        return "".join(parts)

    def _render_replicas(self) -> str:
        """Per-replica gauges with a manual ``{replica="N"}`` label — the
        same inline-label rendering Summary uses for quantiles (the
        primitives themselves are label-free by design)."""
        reps = self._pool.replicas
        gauges = [
            ("simclr_serve_replica_batch_fill",
             "Mean requests per dispatched batch on this replica",
             lambda r: r.batch_fill()),
            ("simclr_serve_replica_in_flight",
             "Requests dispatched to this replica awaiting results",
             lambda r: r.in_flight),
            ("simclr_serve_replica_compute_ms",
             "Device compute milliseconds of this replica's last batch",
             lambda r: r.compute_ms()),
            ("simclr_serve_replica_weight_hbm_bytes",
             "Measured resident weight bytes on this replica's device",
             lambda r: r.engine.weight_hbm_bytes()
             if hasattr(r.engine, "weight_hbm_bytes") else 0),
            ("simclr_serve_replica_weight_hbm_analytic_bytes",
             "Analytic weight bytes under the serve.weights storage mode",
             lambda r: r.engine.weight_hbm_analytic_bytes()
             if hasattr(r.engine, "weight_hbm_analytic_bytes") else 0),
        ]
        parts = []
        for name, help_text, read in gauges:
            parts.append(f"# HELP {name} {help_text}\n# TYPE {name} gauge\n")
            for rep in reps:
                parts.append(f'{name}{{replica="{rep.rid}"}} {read(rep):g}\n')
        return "".join(parts)
