"""Serving metrics: counters, gauges, and latency summaries.

Minimal, dependency-free instrumentation rendered in the Prometheus text
exposition format (``GET /metrics``). Three primitives cover the serving
surface:

  * :class:`Counter` — monotonically increasing totals (requests, rows,
    rejections, batches, compile-cache hits/misses);
  * :class:`Gauge` — point-in-time values, either set explicitly or read
    from a callback at render time (queue depth);
  * :class:`Summary` — streaming latency quantiles (p50/p95/p99) over a
    bounded reservoir of recent observations, plus exact ``_sum``/``_count``.

Everything is thread-safe: handler threads record, the batcher worker
records, and ``/metrics`` renders — all concurrently.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable


class Counter:
    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def render(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n"
            f"# TYPE {self.name} counter\n"
            f"{self.name} {self.value:g}\n"
        )


class Gauge:
    """Explicit ``set()`` or a zero-arg callback sampled at render time."""

    def __init__(self, name: str, help_text: str, fn: Callable[[], float] | None = None):
        self.name = name
        self.help = help_text
        self._fn = fn
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value

    def set_fn(self, fn: Callable[[], float]) -> None:
        """Bind a live source sampled at render time (e.g. queue.qsize)."""
        self._fn = fn

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # callback target may be mid-shutdown
                return 0.0
        with self._lock:
            return self._value

    def render(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n"
            f"# TYPE {self.name} gauge\n"
            f"{self.name} {self.value:g}\n"
        )


class Summary:
    """Quantiles over a sliding reservoir of the most recent observations.

    ``_sum``/``_count`` are exact over the full history; the p50/p95/p99
    quantile lines are computed from the last ``reservoir`` observations —
    recent-window percentiles are what a serving dashboard wants (steady
    state, not startup-compile transients). Quantiles are linear
    interpolations over the sorted reservoir, NaN when empty (the
    Prometheus convention for unobserved summaries).
    """

    QUANTILES = (0.5, 0.95, 0.99)

    def __init__(self, name: str, help_text: str, reservoir: int = 2048):
        self.name = name
        self.help = help_text
        self._samples: deque[float] = deque(maxlen=reservoir)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._samples.append(float(value))
            self._sum += float(value)
            self._count += 1

    def quantile(self, q: float) -> float:
        with self._lock:
            data = sorted(self._samples)
        if not data:
            return float("nan")
        pos = q * (len(data) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(data) - 1)
        return data[lo] + (data[hi] - data[lo]) * (pos - lo)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} summary",
        ]
        for q in self.QUANTILES:
            lines.append(f'{self.name}{{quantile="{q:g}"}} {self.quantile(q):g}')
        lines.append(f"{self.name}_sum {self.sum:g}")
        lines.append(f"{self.name}_count {self.count:g}")
        return "\n".join(lines) + "\n"


class ServeMetrics:
    """The serving stack's metric set, shared by engine, batcher, server.

    Naming follows Prometheus conventions (``_total`` counters, explicit
    units in names). ``avg_batch_fill()`` — requests coalesced per engine
    batch — is the dynamic-batching health number: 1.0 means no coalescing
    is happening (either no concurrency or ``max_delay_ms`` too low).
    """

    def __init__(self):
        self.requests_total = Counter(
            "simclr_serve_requests_total", "Embed requests accepted into the queue")
        self.rows_total = Counter(
            "simclr_serve_rows_total", "Image rows accepted into the queue")
        self.rejected_total = Counter(
            "simclr_serve_rejected_total",
            "Embed requests rejected with backpressure (queue full)")
        self.failed_total = Counter(
            "simclr_serve_failed_total", "Embed requests that failed in the engine")
        self.batches_total = Counter(
            "simclr_serve_batches_total", "Engine batches dispatched")
        self.batch_requests_total = Counter(
            "simclr_serve_batch_requests_total",
            "Requests coalesced into dispatched batches")
        self.batch_rows_total = Counter(
            "simclr_serve_batch_rows_total", "Rows across dispatched batches")
        self.batch_capacity_total = Counter(
            "simclr_serve_batch_capacity_total",
            "Padded bucket capacity across dispatched batches (rows)")
        self.compile_cache_hits_total = Counter(
            "simclr_serve_compile_cache_hits_total",
            "Engine batches whose bucket was already warm (no compile)")
        self.compile_cache_misses_total = Counter(
            "simclr_serve_compile_cache_misses_total",
            "Engine batches that compiled a cold bucket")
        self.queue_depth = Gauge(
            "simclr_serve_queue_depth", "Requests waiting in the batcher queue")
        self.request_latency_ms = Summary(
            "simclr_serve_request_latency_ms",
            "Submit-to-result latency per request (milliseconds)")
        self.batch_latency_ms = Summary(
            "simclr_serve_batch_latency_ms",
            "Engine forward latency per dispatched batch (milliseconds)")

    def avg_batch_fill(self) -> float:
        """Mean requests coalesced per dispatched engine batch."""
        batches = self.batches_total.value
        return self.batch_requests_total.value / batches if batches else 0.0

    def fill_ratio(self) -> float:
        """Mean real-rows / padded-bucket-capacity across batches."""
        capacity = self.batch_capacity_total.value
        return self.batch_rows_total.value / capacity if capacity else 0.0

    def render(self) -> str:
        parts = [
            m.render()
            for m in (
                self.requests_total, self.rows_total, self.rejected_total,
                self.failed_total, self.batches_total,
                self.batch_requests_total, self.batch_rows_total,
                self.batch_capacity_total, self.compile_cache_hits_total,
                self.compile_cache_misses_total, self.queue_depth,
                self.request_latency_ms, self.batch_latency_ms,
            )
        ]
        parts.append(
            "# HELP simclr_serve_avg_batch_fill Mean requests per dispatched batch\n"
            "# TYPE simclr_serve_avg_batch_fill gauge\n"
            f"simclr_serve_avg_batch_fill {self.avg_batch_fill():g}\n"
        )
        parts.append(
            "# HELP simclr_serve_batch_fill_ratio Mean rows over padded bucket capacity\n"
            "# TYPE simclr_serve_batch_fill_ratio gauge\n"
            f"simclr_serve_batch_fill_ratio {self.fill_ratio():g}\n"
        )
        return "".join(parts)
