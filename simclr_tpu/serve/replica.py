"""Replica pool: one :class:`EmbedEngine` per local device, shared queue.

The serve tier's scale-out unit. A single engine serializes every forward
on one chip; a :class:`ReplicaPool` builds N engines over the first N
local devices (``serve.replicas``, -1 = all — ``mesh.serve_replica_devices``),
each with its OWN committed weight copy, bucket jit cache, warmup pass,
and ``_warmup_done`` sentry gate, so aggregate throughput scales with
device count while each request still runs the identical single-device
program (exact weights => responses bitwise identical to the
single-replica path, pinned by test).

Dispatch model (least-loaded by construction): the pool does not route —
``DynamicBatcher`` runs one coalescing worker PER replica, all pulling
from the one shared bounded queue. A worker only takes work when its
replica is free, so the next request always lands on a least-loaded
(idle-first) replica, and each worker coalesces its own batch while the
other replicas compute. Per-replica load/batch/compute state lives here
(:class:`ReplicaState`) and feeds ``/healthz`` and the ``replica``-labeled
``/metrics`` gauges (``serve/metrics.py``).
"""

from __future__ import annotations

import threading
import time


class ReplicaState:
    """One replica's engine plus its live dispatch bookkeeping.

    Mutated only by that replica's single batcher worker (note_* calls) and
    read by /healthz and /metrics render threads — hence the lock around
    the multi-field snapshot.
    """

    def __init__(self, rid: int, engine):
        self.rid = int(rid)
        self.engine = engine
        self._lock = threading.Lock()
        self._in_flight = 0       # requests dispatched to the engine, unresolved
        self._batches = 0
        self._batch_requests = 0
        self._rows = 0
        self._last_dispatch_unix: float | None = None
        self._last_compute_ms: float | None = None

    # -- worker-side bookkeeping -------------------------------------------
    def note_dispatch(self, n_requests: int, n_rows: int) -> None:
        with self._lock:
            self._in_flight += n_requests
            self._batches += 1
            self._batch_requests += n_requests
            self._rows += n_rows
            self._last_dispatch_unix = time.time()

    def note_done(self, n_requests: int, compute_ms: float | None) -> None:
        with self._lock:
            self._in_flight = max(0, self._in_flight - n_requests)
            if compute_ms is not None:
                self._last_compute_ms = compute_ms

    # -- observability reads ------------------------------------------------
    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._in_flight

    @property
    def batches(self) -> int:
        with self._lock:
            return self._batches

    def batch_fill(self) -> float:
        """Mean requests coalesced per batch ON THIS replica."""
        with self._lock:
            return self._batch_requests / self._batches if self._batches else 0.0

    def compute_ms(self) -> float:
        with self._lock:
            return self._last_compute_ms if self._last_compute_ms is not None else 0.0

    def state(self) -> dict:
        """The /healthz per-replica entry."""
        with self._lock:
            last = self._last_dispatch_unix
            snapshot = {
                "replica": self.rid,
                "device": str(getattr(self.engine, "device", None)),
                "warmed_buckets": list(self.engine.warm_state())
                if hasattr(self.engine, "warm_state")
                else [],
                "in_flight": self._in_flight,
                "batches": self._batches,
                "rows": self._rows,
                "last_dispatch_unix": last,
                "weights": getattr(self.engine, "weights_mode", "exact"),
            }
        return snapshot


class ReplicaPool:
    """N engines over N devices behind one front-end queue.

    Construction does NOT start any worker — ``DynamicBatcher(pool=...)``
    owns the threads. The pool is the engine registry plus per-replica
    state; ``primary`` keeps the single-engine surface (buckets, max_batch,
    feature_dim, checkpoint_path) the HTTP layer already speaks.
    """

    def __init__(self, engines):
        if not engines:
            raise ValueError("ReplicaPool needs at least one engine")
        # engines keep whatever replica_id they were built with (None for a
        # wrapped legacy single engine — its sentry names stay untagged).
        # The list itself is copy-on-write under _lock: add/remove build a
        # new list and swap the attribute, so render/healthz threads
        # iterating a snapshot never see a half-mutated registry.
        self.replicas = [ReplicaState(i, e) for i, e in enumerate(engines)]
        self._lock = threading.Lock()

    # -- elastic membership (coscheduler reallocation) ----------------------
    def add_replica(self, engine) -> "ReplicaState":
        """Register a new engine (already built on its device) as the next
        replica id. The caller owns starting a batcher worker for it
        (``DynamicBatcher.add_worker``)."""
        with self._lock:
            rid = max((r.rid for r in self.replicas), default=-1) + 1
            engine.replica_id = rid
            rep = ReplicaState(rid, engine)
            self.replicas = [*self.replicas, rep]
        return rep

    def remove_replica(self, rid: int) -> "ReplicaState":
        """Drop replica ``rid`` from the registry (retire its batcher worker
        FIRST — ``DynamicBatcher.retire_worker`` — so no dispatch targets
        it). The last replica cannot be removed: a pool always serves."""
        with self._lock:
            keep = [r for r in self.replicas if r.rid != rid]
            if len(keep) == len(self.replicas):
                raise KeyError(f"no replica {rid} in the pool")
            if not keep:
                raise ValueError("cannot remove the last replica")
            removed = next(r for r in self.replicas if r.rid == rid)
            self.replicas = keep
        return removed

    @property
    def weights_generation(self) -> int:
        """The pool's SERVING generation: the minimum across replicas, so it
        only advances once every replica has committed the new weights —
        the number /healthz and the staleness gauge report."""
        return min(
            int(getattr(r.engine, "generation", 0)) for r in self.replicas
        )

    # -- single-engine-compatible surface ----------------------------------
    @property
    def primary(self):
        return self.replicas[0].engine

    @property
    def size(self) -> int:
        return len(self.replicas)

    def warmup(self) -> dict[int, dict[int, float]]:
        """Warm every replica's bucket cache; per-replica per-bucket seconds."""
        return {rep.rid: rep.engine.warmup() for rep in self.replicas}

    def state(self) -> list[dict]:
        return [rep.state() for rep in self.replicas]

    def weight_hbm_bytes(self) -> dict[int, int]:
        return {
            rep.rid: rep.engine.weight_hbm_bytes()
            for rep in self.replicas
            if hasattr(rep.engine, "weight_hbm_bytes")
        }

    # -- construction -------------------------------------------------------
    @classmethod
    def from_model(
        cls,
        model,
        variables: dict,
        *,
        replicas: int = -1,
        max_batch: int = 256,
        use_full_encoder: bool = False,
        input_shape: tuple[int, ...] = (32, 32, 3),
        metrics=None,
        warmup: bool = True,
        sentry=None,
        weights: str = "exact",
    ):
        """One engine per device from one host copy of the variables.

        The host pytree is shared; each engine commits its own (possibly
        quantized) device copy, so N replicas cost N weight residencies —
        the HBM number ``serve.weights`` exists to shrink.
        """
        from simclr_tpu.parallel.mesh import serve_replica_devices
        from simclr_tpu.serve.engine import EmbedEngine

        engines = [
            EmbedEngine(
                model,
                variables,
                max_batch=max_batch,
                use_full_encoder=use_full_encoder,
                input_shape=input_shape,
                metrics=metrics,
                warmup=warmup,
                sentry=sentry,
                device=device,
                replica_id=rid,
                weights=weights,
            )
            for rid, device in enumerate(serve_replica_devices(int(replicas)))
        ]
        return cls(engines)

    @classmethod
    def from_checkpoint(cls, cfg, *, metrics=None, warmup: bool = True, sentry=None):
        """Restore the checkpoint ONCE, then fan the host variables out to
        one engine per ``serve.replicas`` device (the pool counterpart of
        ``EmbedEngine.from_checkpoint`` — same blessed loaders, same
        sha256-verified restore path)."""
        from simclr_tpu.eval import build_eval_model, load_model_variables
        from simclr_tpu.utils.checkpoint import latest_checkpoint

        ckpt = cfg.select("serve.checkpoint")
        if not ckpt:
            target_dir = str(cfg.experiment.target_dir)
            ckpt = latest_checkpoint(target_dir)
            if ckpt is None:
                raise FileNotFoundError(
                    f"no checkpoints found under {target_dir!r}; set "
                    f"experiment.target_dir or serve.checkpoint"
                )
        model = build_eval_model(cfg)
        variables = load_model_variables(str(ckpt))
        pool = cls.from_model(
            model,
            variables,
            replicas=int(cfg.select("serve.replicas", -1)),
            max_batch=int(cfg.serve.max_batch),
            use_full_encoder=bool(cfg.parameter.use_full_encoder),
            metrics=metrics,
            warmup=warmup,
            sentry=sentry,
            weights=str(cfg.select("serve.weights", "exact")),
        )
        pool.checkpoint_path = str(ckpt)
        for rep in pool.replicas:
            rep.engine.checkpoint_path = str(ckpt)
        return pool
