"""Dynamic-batching embedding inference server (``python -m simclr_tpu.serve``).

Turns a trained checkpoint into a live HTTP embedding service:

  * :mod:`~simclr_tpu.serve.engine` — checkpoint restore + power-of-two
    bucketed jitted forward, warmup-compiled at startup;
  * :mod:`~simclr_tpu.serve.batcher` — bounded queue, dynamic
    micro-batching, backpressure, graceful drain;
  * :mod:`~simclr_tpu.serve.replica` — one engine per local device
    (``serve.replicas``) behind the shared queue, with per-replica
    warmup, compile cache, and live dispatch state;
  * :mod:`~simclr_tpu.serve.retrieval` — row-sharded in-HBM embedding
    corpus answering exact top-k on device (``POST /v1/neighbors``);
  * :mod:`~simclr_tpu.serve.server` — stdlib ThreadingHTTPServer JSON API
    (``POST /v1/embed``, ``POST /v1/neighbors``, ``GET /healthz``,
    ``GET /metrics``), SIGTERM → drain → exit 0;
  * :mod:`~simclr_tpu.serve.metrics` — Prometheus-text counters, gauges,
    and latency summaries, with ``{replica="N"}``-labeled fan-out gauges.

Knobs live under the ``serve:`` group of ``conf/serve.yaml``; operational
docs in ``docs/SERVING.md``. Imports here are lazy so touching the light
pieces (batcher, metrics) never pays the jax import.
"""

from __future__ import annotations

__all__ = [
    "BackpressureError",
    "BatcherClosedError",
    "DynamicBatcher",
    "EmbedEngine",
    "NeighborIndex",
    "ReplicaPool",
    "ServeMetrics",
    "run_server",
    "start_server",
]

_EXPORTS = {
    "BackpressureError": "simclr_tpu.serve.batcher",
    "BatcherClosedError": "simclr_tpu.serve.batcher",
    "DynamicBatcher": "simclr_tpu.serve.batcher",
    "EmbedEngine": "simclr_tpu.serve.engine",
    "NeighborIndex": "simclr_tpu.serve.retrieval",
    "ReplicaPool": "simclr_tpu.serve.replica",
    "ServeMetrics": "simclr_tpu.serve.metrics",
    "run_server": "simclr_tpu.serve.server",
    "start_server": "simclr_tpu.serve.server",
}


def __getattr__(name: str):
    if name in _EXPORTS:
        import importlib

        return getattr(importlib.import_module(_EXPORTS[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
