"""HTTP front end: stdlib ThreadingHTTPServer JSON API over the batcher.

Endpoints:

  * ``POST /v1/embed`` — body ``{"instances": [image, ...]}`` where each
    image is a nested list of uint8 pixels shaped like the engine's input
    (CIFAR: 32x32x3). Response ``{"embeddings": [[...], ...], "model": ...}``
    row-aligned with the instances, with an ``X-Served-By: <replica>``
    header naming the replica that computed it. Errors: 400 malformed
    body/shape/range, 413 more rows than ``serve.max_batch``, 429 queue
    full (backpressure — retry with backoff), 500 engine failure, 503
    draining.
  * ``POST /v1/neighbors`` — body ``{"queries": [[d floats], ...],
    "k": int}`` (``k`` optional, default ``serve.neighbors_k``). Exact
    top-k over the row-sharded in-HBM corpus (``serve.corpus``,
    ``serve/retrieval.py``); response ``{"indices": [[...]], "scores":
    [[...]]}`` row-aligned with the queries. 404 when no corpus is
    configured, 400 malformed queries/k, 503 draining.
  * ``POST /v1/corpus/upsert`` — body ``{"ids": [int, ...], "embeddings":
    [[d floats], ...]}``: insert-or-update corpus rows by external id.
    ``POST /v1/corpus/delete`` — body ``{"ids": [int, ...]}``. Both commit
    a fresh generation-tagged index with one atomic swap (zero downtime —
    in-flight queries finish on the generation they started with) and
    answer ``{"generation": g, "rows": n}`` + ``X-Corpus-Generation``.
    404 when the corpus is not mutable (no store), 400 bad ids/shapes,
    503 draining.
  * ``GET /healthz`` — 200 once warm and accepting (with per-replica
    state under ``"replicas"`` and corpus residency under ``"neighbors"``),
    503 while draining.
  * ``GET /metrics`` — Prometheus text format (``serve/metrics.py``).
  * ``GET /debug/slow`` — the slowest recent requests with their span
    breakdowns (``obs/trace.py`` ring buffer).

Request tracing: every ``/v1/embed`` request gets an ``X-Request-Id``
(client-supplied, sanitized, or generated), echoed on the response and
used in log lines, and records queue_wait / coalesce / pad /
device_compute / serialize spans into the server's
:class:`~simclr_tpu.obs.trace.TraceRecorder` — which also samples
completed traces into ``serve.requests_log`` at
``serve.trace_sample_rate``.

Shutdown contract (tested): SIGTERM (or SIGINT) flips the server into
draining — new embeds get 503, ``/healthz`` reports draining — then the
batcher drains (every accepted request is answered), the accept loop
stops, in-flight handler threads are joined, and the process exits 0.

JSON float fidelity: embeddings are float32; Python serializes each via
the shortest repr of its exact double value, so a client reading the JSON
back into float32 recovers the embedding **bitwise** — the e2e test
asserts equality through the full HTTP round trip.
"""

from __future__ import annotations

import json
import signal
import threading
from concurrent.futures import TimeoutError as _FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from simclr_tpu.obs.trace import RequestTrace, TraceRecorder, clean_request_id
from simclr_tpu.serve.batcher import BackpressureError, BatcherClosedError
from simclr_tpu.utils.logging import get_logger

logger = get_logger()


class EmbedServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the serving stack for its handlers.

    ``daemon_threads=True`` with the default ``block_on_close=True``:
    handler threads never outlive a crash, but a clean ``server_close()``
    still joins them — required for the drain guarantee.
    """

    daemon_threads = True
    allow_reuse_address = True

    def __init__(
        self,
        address,
        engine,
        batcher,
        metrics,
        request_timeout_s=30.0,
        recorder: TraceRecorder | None = None,
        pool=None,
        index=None,
        neighbors_k_default=10,
        corpus_store=None,
    ):
        super().__init__(address, EmbedHandler)
        self.engine = engine
        self.batcher = batcher
        self.metrics = metrics
        self.pool = pool          # serve/replica.py ReplicaPool (healthz fan-out)
        self.index = index        # serve/retrieval.py NeighborIndex, or None
        # serve/retrieval.py MutableCorpus: enables /v1/corpus/* mutations
        self.corpus_store = corpus_store
        self.neighbors_k_default = int(neighbors_k_default)
        self.request_timeout_s = float(request_timeout_s)
        self.recorder = recorder if recorder is not None else TraceRecorder()
        self.draining = threading.Event()

    def swap_index(self, index) -> None:
        """Atomically replace the retrieval index (generation-tagged corpus
        swap). Handlers read ``self.server.index`` exactly once per request,
        so an in-flight ``/v1/neighbors`` finishes on the index it started
        with and the next request sees the new generation — the corpus
        counterpart of ``EmbedEngine.commit``."""
        self.index = index


class EmbedHandler(BaseHTTPRequestHandler):
    server: EmbedServer
    server_version = "simclr-serve/1.0"
    protocol_version = "HTTP/1.1"
    # TCP_NODELAY: without it, Nagle + delayed ACK stalls small
    # response-then-request exchanges on keep-alive connections by ~40ms —
    # an order of magnitude over the coalescing window itself
    disable_nagle_algorithm = True

    # quiet per-request lines; keep them reachable at debug level
    def log_message(self, fmt, *args):  # noqa: D102
        logger.debug("http %s", fmt % args)

    def _send(self, code: int, body: bytes, content_type: str, headers=()) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            rid = getattr(self, "_request_id", None)
            if rid:
                self.send_header("X-Request-Id", rid)
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            # client hung up mid-response: routine (impatient callers,
            # load-balancer health probes) — count it, don't traceback
            if self.server.metrics is not None:
                self.server.metrics.client_disconnects_total.inc()
            self.close_connection = True

    def _send_json(self, code: int, payload: dict, headers=()) -> None:
        self._send(
            code, json.dumps(payload).encode(), "application/json", headers
        )

    # -- GET ---------------------------------------------------------------
    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        # one handler instance serves a whole keep-alive connection; clear
        # any id left by a previous POST on the same socket
        self._request_id = None
        if self.path == "/healthz":
            if self.server.draining.is_set():
                self._send_json(503, {"status": "draining"})
            else:
                payload = {
                    "status": "ok",
                    "buckets": list(self.server.engine.buckets),
                    "max_batch": self.server.engine.max_batch,
                    "feature_dim": self.server.engine.feature_dim,
                    "checkpoint": getattr(
                        self.server.engine, "checkpoint_path", None
                    ),
                }
                if self.server.pool is not None:
                    payload["replicas"] = self.server.pool.state()
                    # serving generation = min across replicas: advances
                    # only once EVERY replica committed the new weights
                    payload["weights_generation"] = (
                        self.server.pool.weights_generation
                    )
                index = self.server.index
                if index is not None:
                    payload["neighbors"] = index.hbm_state()
                    payload["corpus_generation"] = int(
                        getattr(index, "generation", 0)
                    )
                self._send_json(200, payload)
        elif self.path == "/metrics":
            self._send(
                200,
                self.server.metrics.render().encode(),
                "text/plain; version=0.0.4",
            )
        elif self.path == "/debug/slow":
            self._send_json(200, {"slowest": self.server.recorder.slowest()})
        else:
            self._send_json(404, {"error": f"no such path {self.path!r}"})

    # -- POST --------------------------------------------------------------
    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
        # resolved first so EVERY response (including errors) echoes the id
        rid = clean_request_id(self.headers.get("X-Request-Id"))
        self._request_id = rid
        if self.path == "/v1/neighbors":
            self._post_neighbors(rid)
            return
        if self.path in ("/v1/corpus/upsert", "/v1/corpus/delete"):
            self._post_corpus(rid, self.path.rsplit("/", 1)[1])
            return
        if self.path != "/v1/embed":
            self._send_json(404, {"error": f"no such path {self.path!r}"})
            return
        if self.server.draining.is_set():
            self._send_json(
                503, {"error": "server is draining"}, [("Retry-After", "1")]
            )
            return
        try:
            images = self._parse_instances()
        except _BadRequest as e:
            logger.debug("embed %s rejected (%d): %s", rid, e.code, e)
            self._send_json(e.code, {"error": str(e)})
            return
        trace = RequestTrace(rid)
        try:
            future = self.server.batcher.submit(images, trace=trace)
        except BackpressureError as e:
            self._send_json(429, {"error": str(e)}, [("Retry-After", "1")])
            return
        except BatcherClosedError as e:
            self._send_json(503, {"error": str(e)}, [("Retry-After", "1")])
            return
        try:
            embeddings = future.result(timeout=self.server.request_timeout_s)
        except (TimeoutError, _FutureTimeout):
            logger.warning(
                "embed %s timed out after %.1fs",
                rid,
                self.server.request_timeout_s,
            )
            self._send_json(
                504,
                {"error": f"embed timed out after {self.server.request_timeout_s}s"},
            )
            return
        except BatcherClosedError as e:
            self._send_json(503, {"error": str(e)})
            return
        except Exception as e:  # engine failure — already counted by batcher
            logger.warning("embed %s failed in engine: %r", rid, e)
            self._send_json(500, {"error": repr(e)})
            return
        # ndarray.tolist() converts float32 -> exact Python double in C
        # (same shortest-repr doubles as the old per-element loop, so the
        # JSON round trip stays bitwise exact — tested), without an O(n*d)
        # Python-level loop
        with trace.span("serialize"):
            body = json.dumps(
                {"embeddings": np.asarray(embeddings).tolist()}
            ).encode()
        rec = self.server.recorder.record(trace)
        logger.debug(
            "embed %s: %d rows in %.1f ms", rid, len(embeddings), rec["total_ms"]
        )
        # stamped by the dispatching replica's worker before the future
        # resolved (pool mode); absent on the legacy single-engine path
        served_by = getattr(future, "replica_id", None)
        headers = (
            [("X-Served-By", str(served_by))] if served_by is not None else []
        )
        # the weight generation the dispatching replica served this request
        # with — what the co-scheduler smoke compares against the corpus
        # generation for embed/neighbors consistency
        generation = getattr(future, "generation", None)
        if generation is not None:
            headers.append(("X-Weights-Generation", str(generation)))
        self._send(200, body, "application/json", headers)

    def _post_neighbors(self, rid) -> None:
        index = self.server.index
        if index is None:
            self._send_json(
                404,
                {"error": "no retrieval corpus configured (set serve.corpus)"},
            )
            return
        if self.server.draining.is_set():
            self._send_json(
                503, {"error": "server is draining"}, [("Retry-After", "1")]
            )
            return
        try:
            queries, k = self._parse_neighbors(index)
        except _BadRequest as e:
            logger.debug("neighbors %s rejected (%d): %s", rid, e.code, e)
            self._send_json(e.code, {"error": str(e)})
            return
        try:
            scores, indices = index.query(queries, k)
        except ValueError as e:
            self._send_json(400, {"error": str(e)})
            return
        except Exception as e:  # device failure
            logger.warning("neighbors %s failed: %r", rid, e)
            self._send_json(500, {"error": repr(e)})
            return
        payload = {
            "indices": indices.tolist(),
            "scores": scores.tolist(),
            "k": k,
            "metric": index.metric,
        }
        row_ids = getattr(index, "row_ids", None)
        if row_ids is not None:
            # external ids for a mutable corpus; ANN padding slots (idx -1)
            # stay -1
            payload["ids"] = np.where(
                indices >= 0,
                row_ids[np.clip(indices, 0, len(row_ids) - 1)],
                -1,
            ).tolist()
        self._send_json(
            200,
            payload,
            [("X-Corpus-Generation", str(getattr(index, "generation", 0)))],
        )

    def _post_corpus(self, rid, action: str) -> None:
        store = self.server.corpus_store
        if store is None:
            self._send_json(
                404,
                {"error": "corpus is not mutable "
                          "(serve without a corpus store; set serve.corpus)"},
            )
            return
        if self.server.draining.is_set():
            self._send_json(
                503, {"error": "server is draining"}, [("Retry-After", "1")]
            )
            return
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0:
            self._send_json(400, {"error": "missing request body"})
            return
        try:
            payload = json.loads(self.rfile.read(length))
        except (ValueError, UnicodeDecodeError) as e:
            self._send_json(400, {"error": f"body is not valid JSON: {e}"})
            return
        needed = ("ids", "embeddings") if action == "upsert" else ("ids",)
        if not isinstance(payload, dict) or any(k not in payload for k in needed):
            self._send_json(
                400,
                {"error": f'body must be a JSON object with {" and ".join(needed)}'},
            )
            return
        try:
            if action == "upsert":
                out = store.upsert(payload["ids"], payload["embeddings"])
            else:
                out = store.delete(payload["ids"])
        except (ValueError, TypeError) as e:
            logger.debug("corpus %s %s rejected: %s", action, rid, e)
            self._send_json(400, {"error": str(e)})
            return
        except Exception as e:  # device failure mid-rebuild
            logger.warning("corpus %s %s failed: %r", action, rid, e)
            self._send_json(500, {"error": repr(e)})
            return
        out = dict(out)
        out["status"] = "committed"
        self._send_json(
            200, out, [("X-Corpus-Generation", str(out["generation"]))]
        )

    def _parse_neighbors(self, index) -> tuple:
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0:
            raise _BadRequest("missing request body")
        try:
            payload = json.loads(self.rfile.read(length))
        except (ValueError, UnicodeDecodeError) as e:
            raise _BadRequest(f"body is not valid JSON: {e}") from None
        if not isinstance(payload, dict) or "queries" not in payload:
            raise _BadRequest('body must be a JSON object with "queries"')
        try:
            queries = np.asarray(payload["queries"], np.float32)
        except (ValueError, TypeError) as e:
            raise _BadRequest(f"queries are not a rectangular float array: {e}") from None
        if queries.ndim != 2 or queries.shape[1] != index.d:
            raise _BadRequest(
                f"queries must be shaped (n, {index.d}), got {queries.shape}"
            )
        if not 1 <= queries.shape[0] <= index.max_queries:
            raise _BadRequest(
                f"queries must carry 1..{index.max_queries} rows, "
                f"got {queries.shape[0]}"
            )
        if not np.isfinite(queries).all():
            raise _BadRequest("queries must be finite floats")
        k = payload.get("k", self.server.neighbors_k_default)
        if not isinstance(k, int) or isinstance(k, bool):
            raise _BadRequest(f"k must be an integer, got {k!r}")
        if not 1 <= k <= index.n:
            raise _BadRequest(
                f"k must be in [1, {index.n}] for a {index.n}-row corpus, got {k}"
            )
        return queries, k

    def _parse_instances(self) -> np.ndarray:
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0:
            raise _BadRequest("missing request body")
        try:
            payload = json.loads(self.rfile.read(length))
        except (ValueError, UnicodeDecodeError) as e:
            raise _BadRequest(f"body is not valid JSON: {e}") from None
        if not isinstance(payload, dict) or "instances" not in payload:
            raise _BadRequest('body must be a JSON object with "instances"')
        engine = self.server.engine
        try:
            images = np.asarray(payload["instances"])
        except (ValueError, TypeError) as e:
            raise _BadRequest(f"instances are not a rectangular array: {e}") from None
        if images.ndim != 1 + len(engine.input_shape) or (
            images.shape[1:] != engine.input_shape
        ):
            raise _BadRequest(
                f"instances must be shaped (n, "
                f"{', '.join(map(str, engine.input_shape))}), got {images.shape}"
            )
        if images.shape[0] < 1:
            raise _BadRequest("instances must carry at least one image")
        if images.shape[0] > engine.max_batch:
            raise _BadRequest(
                f"{images.shape[0]} instances exceeds max_batch="
                f"{engine.max_batch}; split the request",
                code=413,
            )
        if not np.issubdtype(images.dtype, np.integer):
            raise _BadRequest(f"pixels must be integers 0..255, got {images.dtype}")
        if images.min() < 0 or images.max() > 255:
            raise _BadRequest("pixel values must be uint8 (0..255)")
        return images.astype(np.uint8)


class _BadRequest(ValueError):
    def __init__(self, message: str, code: int = 400):
        super().__init__(message)
        self.code = code


def run_server(cfg) -> int:
    """Build the stack from ``cfg``, serve until SIGTERM/SIGINT, drain, 0.

    The ``python -m simclr_tpu.serve`` body, also callable in-process (the
    e2e tests drive it via :func:`start_server` below instead, which skips
    the signal wiring the test process cannot own).
    """
    from simclr_tpu.config import check_serve_conf
    from simclr_tpu.serve.metrics import ServeMetrics
    from simclr_tpu.serve.replica import ReplicaPool

    check_serve_conf(cfg)
    metrics = ServeMetrics()
    logger.info("restoring checkpoint and building replicas...")
    pool = ReplicaPool.from_checkpoint(cfg, metrics=metrics, warmup=False)
    warm_times = pool.warmup()
    for rid, times in sorted(warm_times.items()):
        logger.info(
            "replica %d: warmed %d bucket programs (max_batch=%d): %s",
            rid, len(times), pool.primary.max_batch,
            " ".join(f"b{b}={t:.2f}s" for b, t in sorted(times.items())),
        )
    server, _batcher = start_server(cfg, pool=pool, metrics=metrics)

    def _terminate(signum, frame):
        # shutdown() must not run on the serve_forever thread (it blocks on
        # the loop stopping); hand the drain to a helper thread and return
        # from the handler immediately
        logger.info("signal %d: draining...", signum)
        threading.Thread(
            target=shutdown_gracefully, args=(server,), daemon=True
        ).start()

    previous = {
        sig: signal.signal(sig, _terminate)
        for sig in (signal.SIGTERM, signal.SIGINT)
    }
    try:
        logger.info(
            "serving embeddings on http://%s:%d (POST /v1/embed)",
            *server.server_address[:2],
        )
        _write_ready_file(cfg, server)
        server.serve_forever(poll_interval=0.1)
    finally:
        for sig, handler in previous.items():
            signal.signal(sig, handler)
        server.server_close()  # joins in-flight handler threads
    logger.info("drained; exiting 0")
    return 0


def start_server(
    cfg, *, engine=None, metrics=None, pool=None, index=None, corpus_store=None
) -> tuple:
    """Construct (EmbedServer, DynamicBatcher) bound to ``serve.host:port``
    without entering the accept loop — the embeddable/testable core of
    :func:`run_server`. Caller runs ``serve_forever`` and later
    :func:`shutdown_gracefully`.

    ``pool`` (a :class:`~simclr_tpu.serve.replica.ReplicaPool`) is the
    replicated path; a bare ``engine`` is wrapped into a pool of one, so
    every server runs the same per-replica worker machinery. ``index``
    (a :class:`~simclr_tpu.serve.retrieval.NeighborIndex`) enables
    ``/v1/neighbors``; when None it is built from ``serve.corpus`` if set —
    through a :class:`~simclr_tpu.serve.retrieval.MutableCorpus`, so a
    file-configured corpus accepts ``/v1/corpus/*`` mutations out of the
    box. An explicit ``corpus_store`` supplies both the index and the
    mutation path.
    """
    from simclr_tpu.serve.batcher import DynamicBatcher
    from simclr_tpu.serve.metrics import ServeMetrics
    from simclr_tpu.serve.replica import ReplicaPool

    metrics = metrics if metrics is not None else ServeMetrics()
    if pool is None:
        if engine is not None:
            pool = ReplicaPool([engine])
        else:
            pool = ReplicaPool.from_checkpoint(cfg, metrics=metrics)
    metrics.attach_pool(pool)
    primary = pool.primary
    batcher = DynamicBatcher(
        pool=pool,
        max_batch=primary.max_batch,
        max_delay_ms=float(cfg.serve.max_delay_ms),
        queue_depth=int(cfg.serve.queue_depth),
        metrics=metrics,
    )
    if index is None and corpus_store is not None:
        index = corpus_store.index
    if index is None:
        corpus = cfg.select("serve.corpus")
        if corpus:
            from simclr_tpu.serve.retrieval import MutableCorpus

            corpus_store = MutableCorpus.from_file(
                str(corpus),
                metrics=metrics,
                metric=str(cfg.select("serve.neighbors_metric", "dot")),
                max_queries=primary.max_batch,
                sentry=primary.sentry,
                corpus_dtype=str(cfg.select("serve.corpus_dtype", "fp32")),
                ann_cells=int(cfg.select("serve.ann_cells", 0) or 0),
                ann_probe=int(cfg.select("serve.ann_probe", 1) or 1),
            )
            index = corpus_store.index
            scan = (
                f"ivf {index.ann_cells}x{index.cell_rows} probe {index.ann_probe}"
                if index.ann_cells else "exact"
            )
            logger.info(
                "retrieval corpus resident: %d rows x %d dims over %d shards "
                "(%s, %s, %.1f MiB HBM)",
                index.n, index.d, index.n_shards, index.dtype, scan,
                index.hbm_state()["corpus_hbm_bytes"] / 2**20,
            )
    requests_log = cfg.select("serve.requests_log")
    recorder = TraceRecorder(
        sample_rate=float(cfg.select("serve.trace_sample_rate", 0.0) or 0.0),
        path=str(requests_log) if requests_log else None,
    )
    server = EmbedServer(
        (str(cfg.serve.host), int(cfg.serve.port)),
        primary,
        batcher,
        metrics,
        request_timeout_s=float(cfg.serve.request_timeout_s),
        recorder=recorder,
        pool=pool,
        index=index,
        neighbors_k_default=int(cfg.select("serve.neighbors_k", 10)),
        corpus_store=corpus_store,
    )
    if corpus_store is not None:
        # mutations committed from here on swap this server's index
        corpus_store.server = server
    return server, batcher


def shutdown_gracefully(server: EmbedServer, drain_timeout_s: float = 30.0) -> None:
    """Drain-then-stop, idempotent: 503 new work, answer everything
    accepted, stop the accept loop."""
    if server.draining.is_set():
        return
    server.draining.set()
    server.batcher.close(drain=True, timeout=drain_timeout_s)
    server.shutdown()


def _write_ready_file(cfg, server: EmbedServer) -> None:
    """Publish the bound address (``serve.ready_file``) — how orchestration
    and the SIGTERM e2e test learn an ephemeral port (``serve.port=0``)."""
    import os

    path = cfg.select("serve.ready_file")
    if not path:
        return
    from simclr_tpu.utils.ioutil import atomic_write

    host, port = server.server_address[:2]
    atomic_write(
        str(path),
        lambda f: json.dump(
            {"host": host, "port": port, "pid": os.getpid()}, f
        ),
    )
