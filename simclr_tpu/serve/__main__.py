"""``python -m simclr_tpu.serve`` — the embedding server entry point.

Same override surface as every other entry point::

    python -m simclr_tpu.serve \
        experiment.target_dir=results/cifar10/seed-7/<date>/<time> \
        serve.port=8000 serve.max_batch=256 serve.max_delay_ms=5
"""

from __future__ import annotations

import sys


def main(argv: list[str] | None = None) -> int:
    from simclr_tpu.utils.platform import ensure_platform

    ensure_platform()
    from simclr_tpu.config import load_config
    from simclr_tpu.serve.server import run_server

    cfg = load_config(
        "serve", overrides=list(sys.argv[1:] if argv is None else argv)
    )
    return run_server(cfg)


if __name__ == "__main__":
    sys.exit(main())
