"""Dynamic micro-batching: coalesce concurrent requests into engine batches.

The serving analogue of ``data/prefetch.py``'s queue-and-drain discipline,
inverted: many producer threads (HTTP handlers) feed one consumer (the
engine worker). Requests enter a **bounded** queue — a full queue rejects
immediately (:class:`BackpressureError`, surfaced as HTTP 429) instead of
letting latency grow without bound — and the worker coalesces whatever is
queued into one batch, waiting at most ``max_delay_ms`` after the first
request before dispatching, never exceeding ``max_batch`` rows.

Why coalesce at all: the engine's cost per forward is dominated by fixed
dispatch + weight-streaming overhead at small batches, so N concurrent
1-row requests served as one N-row bucket cost barely more than one of
them alone (the Podracer batched-inference observation). ``max_delay_ms``
bounds the latency price the first request pays for that throughput.

Shutdown is a graceful drain: ``close()`` stops intake, the worker answers
everything already queued, and only then exits — no accepted request is
ever dropped (the SIGTERM contract in ``server.py``).

Replica fan-out (``pool=``): with a ``serve/replica.py`` :class:`ReplicaPool`
the batcher runs ONE coalescing worker PER replica, all pulling from the
same bounded queue. Work-stealing off the shared queue IS the least-loaded
dispatch policy: a worker only takes the next request when its replica is
free, so idle replicas pick up work first and each worker coalesces its own
batch while the others compute. Every dispatch stamps its replica's live
state (in-flight, batch fill, compute ms — the ``/healthz`` and labeled
``/metrics`` feeds) and tags each answered future with ``replica_id`` (the
``X-Served-By`` response header).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

import numpy as np

_POLL_S = 0.05


class BackpressureError(RuntimeError):
    """The request queue is full — shed load now, retry later (HTTP 429)."""


class BatcherClosedError(RuntimeError):
    """The batcher is shutting down and no longer accepts requests (503)."""


@dataclass
class _Pending:
    images: np.ndarray
    future: Future = field(default_factory=Future)
    submitted_at: float = field(default_factory=time.perf_counter)
    # optional obs.trace.RequestTrace riding along; picked_at is stamped by
    # the worker when the request leaves the queue (queue_wait span end)
    trace: object | None = None
    picked_at: float | None = None

    @property
    def n_rows(self) -> int:
        return self.images.shape[0]


class DynamicBatcher:
    """Bounded request queue + dispatch worker(s) over ``embed_fn``/``pool``.

    Single-engine mode: ``embed_fn(images) -> embeddings`` is called from
    exactly one thread (the worker), with at most ``max_batch`` rows per
    call; per-request row slices of its output resolve the corresponding
    futures. Pool mode (``pool=`` a :class:`~simclr_tpu.serve.replica
    .ReplicaPool`, ``embed_fn=None``): one such worker per replica over
    the one shared queue, each calling its own replica's ``engine.embed``.
    """

    def __init__(
        self,
        embed_fn=None,
        *,
        pool=None,
        max_batch: int = 256,
        max_delay_ms: float = 5.0,
        queue_depth: int = 64,
        metrics=None,
        span_source=None,
    ):
        if (embed_fn is None) == (pool is None):
            raise ValueError("pass exactly one of embed_fn or pool")
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_delay_ms < 0:
            raise ValueError(f"max_delay_ms must be >= 0, got {max_delay_ms}")
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        self._embed_fn = embed_fn
        self.pool = pool
        # () -> iterable of (name, start, end) spans describing the LAST
        # embed_fn call (the engine's pad/device_compute breakdown); read
        # only from the worker thread, right after each dispatch. Pool mode
        # reads each replica's own engine.last_spans instead.
        self._span_source = span_source
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.metrics = metrics
        self._q: queue.Queue[_Pending] = queue.Queue(maxsize=queue_depth)
        self._closed = threading.Event()   # stop intake; workers drain then exit
        self._abort = threading.Event()    # stop now; queued futures fail
        # per-replica retire events (elastic membership): setting one makes
        # that replica's worker exit between batches without touching the
        # global lifecycle — the other workers keep draining the queue
        self._retire: dict[int, threading.Event] = {}
        self._worker_lock = threading.Lock()
        if pool is None:
            self._workers = [
                threading.Thread(target=self._run, name="serve-batcher", daemon=True)
            ]
        else:
            self._workers = [
                self._make_worker(rep) for rep in pool.replicas
            ]
        for w in self._workers:
            w.start()
        if metrics is not None:
            metrics.queue_depth.set_fn(self._q.qsize)

    def _make_worker(self, rep) -> threading.Thread:
        self._retire[rep.rid] = threading.Event()
        return threading.Thread(
            target=self._run,
            args=(rep,),
            name=f"serve-batcher-r{rep.rid}",
            daemon=True,
        )

    # -- elastic membership (coscheduler reallocation) ----------------------
    def add_worker(self, rep) -> None:
        """Start a coalescing worker for a replica added to the pool after
        construction (``ReplicaPool.add_replica``). The new worker pulls
        from the same shared queue — dispatch stays least-loaded."""
        with self._worker_lock:
            w = self._make_worker(rep)
            self._workers.append(w)
        w.start()

    def retire_worker(self, rid: int, timeout: float = 30.0) -> bool:
        """Stop replica ``rid``'s worker between batches.

        The worker finishes any batch it already took (no accepted request
        is dropped), then exits; queued items it never took stay for the
        remaining workers. Returns True once the worker has exited."""
        try:
            self._retire[rid].set()
        except KeyError:
            raise KeyError(f"no worker for replica {rid}") from None
        with self._worker_lock:
            workers = list(self._workers)
        deadline = time.perf_counter() + timeout
        for w in workers:
            if w.name == f"serve-batcher-r{rid}":
                w.join(timeout=max(0.0, deadline - time.perf_counter()))
                if not w.is_alive():
                    with self._worker_lock:
                        self._workers = [x for x in self._workers if x is not w]
                return not w.is_alive()
        return True

    # -- producer side (HTTP handler threads) ------------------------------
    def submit(self, images: np.ndarray, trace=None) -> Future:
        """Enqueue one request; returns a Future of its ``(n, d)`` embeddings.

        ``trace`` (an ``obs.trace.RequestTrace``) collects the request's
        queue_wait/coalesce spans plus the engine's per-batch spans.

        Raises :class:`BatcherClosedError` during shutdown and
        :class:`BackpressureError` when the queue is full — both BEFORE
        accepting the work, so every accepted future is guaranteed an
        answer (result or exception).
        """
        if self._closed.is_set():
            raise BatcherClosedError("batcher is draining; not accepting requests")
        item = _Pending(np.asarray(images), trace=trace)
        if not 0 < item.n_rows <= self.max_batch:
            raise ValueError(
                f"request must carry 1..{self.max_batch} rows, got {item.n_rows}"
            )
        try:
            self._q.put_nowait(item)
        except queue.Full:
            if self.metrics is not None:
                self.metrics.rejected_total.inc()
            raise BackpressureError(
                f"request queue full ({self._q.maxsize} pending); retry later"
            ) from None
        if self.metrics is not None:
            self.metrics.requests_total.inc()
            self.metrics.rows_total.inc(item.n_rows)
        return item.future

    # -- consumer side (one worker thread per replica) ---------------------
    def _run(self, replica=None) -> None:
        carry: _Pending | None = None
        retire = (
            self._retire.get(replica.rid) if replica is not None else None
        )
        while not self._abort.is_set():
            if retire is not None and retire.is_set() and carry is None:
                return  # retired between batches; queue stays for the others
            if carry is not None:
                first, carry = carry, None
            else:
                try:
                    first = self._q.get(timeout=_POLL_S)
                except queue.Empty:
                    if self._closed.is_set():
                        return  # drained: intake stopped and queue empty
                    continue
                first.picked_at = time.perf_counter()
            batch = [first]
            rows = first.n_rows
            deadline = time.perf_counter() + self.max_delay_s
            while rows < self.max_batch and not self._abort.is_set():
                try:
                    nxt = self._q.get(
                        timeout=max(0.0, deadline - time.perf_counter())
                    )
                except queue.Empty:
                    break
                nxt.picked_at = time.perf_counter()
                if rows + nxt.n_rows > self.max_batch:
                    carry = nxt  # opens the next batch; never dropped
                    break
                batch.append(nxt)
                rows += nxt.n_rows
            self._dispatch(batch, replica)
        # aborted: fail whatever never got dispatched (each worker fails its
        # own carry; the shared queue hands each worker distinct items)
        for item in ([carry] if carry is not None else []) + self._drain():
            item.future.set_exception(BatcherClosedError("batcher aborted"))

    def _dispatch(self, batch: list[_Pending], replica=None) -> None:
        if self.metrics is not None:
            self.metrics.batch_requests_total.inc(len(batch))
        n_rows = sum(p.n_rows for p in batch)
        if replica is not None:
            replica.note_dispatch(len(batch), n_rows)
        dispatched_at = time.perf_counter()
        try:
            images = (
                batch[0].images
                if len(batch) == 1
                else np.concatenate([p.images for p in batch])
            )
            embed_fn = self._embed_fn if replica is None else replica.engine.embed
            out = embed_fn(images)
        except BaseException as e:  # noqa: BLE001 - relayed to every caller
            if self.metrics is not None:
                self.metrics.failed_total.inc(len(batch))
            if replica is not None:
                replica.note_done(len(batch), None)
            for p in batch:
                p.future.set_exception(e)
            return
        done = time.perf_counter()
        engine_spans = ()
        span_source = (
            self._span_source
            if replica is None
            else (lambda: replica.engine.last_spans)
        )
        if span_source is not None:
            try:
                engine_spans = tuple(span_source())
            except Exception:  # never let tracing break a dispatch
                engine_spans = ()
        if replica is not None:
            compute_ms = next(
                (
                    (end - start) * 1000.0
                    for name, start, end in engine_spans
                    if name == "device_compute"
                ),
                None,
            )
            replica.note_done(len(batch), compute_ms)
        offset = 0
        for p in batch:
            if replica is not None:
                # stamped BEFORE set_result so the handler thread always
                # sees them when the future resolves (X-Served-By /
                # X-Weights-Generation headers)
                p.future.replica_id = replica.rid
                p.future.generation = getattr(
                    replica.engine, "generation", None
                )
            if p.trace is not None:
                # spans are complete before the future resolves, so the
                # handler thread reads a finished trace
                picked = p.picked_at if p.picked_at is not None else dispatched_at
                p.trace.add("queue_wait", p.submitted_at, picked)
                p.trace.add("coalesce", picked, dispatched_at)
                for name, start, end in engine_spans:
                    p.trace.add(name, start, end)
            p.future.set_result(out[offset : offset + p.n_rows])
            offset += p.n_rows
            if self.metrics is not None:
                self.metrics.request_latency_ms.observe(
                    (done - p.submitted_at) * 1000.0
                )

    def _drain(self) -> list[_Pending]:
        items = []
        try:
            while True:
                items.append(self._q.get_nowait())
        except queue.Empty:
            return items

    # -- lifecycle ---------------------------------------------------------
    def close(self, drain: bool = True, timeout: float = 30.0) -> bool:
        """Stop intake and shut the worker down.

        ``drain=True`` (the SIGTERM path): every already-queued request is
        dispatched and answered before the workers exit. ``drain=False``:
        the workers stop at the next poll and queued futures fail with
        :class:`BatcherClosedError`. Returns True if every worker exited
        within ``timeout`` (they are daemon threads either way, so a wedged
        engine cannot hang interpreter shutdown).
        """
        self._closed.set()
        if not drain:
            self._abort.set()
        deadline = time.perf_counter() + timeout
        for w in self._workers:
            w.join(timeout=max(0.0, deadline - time.perf_counter()))
        if drain and any(w.is_alive() for w in self._workers):
            # drain overran the timeout: abort so stragglers fail fast
            # rather than dangling unanswered
            self._abort.set()
            for w in self._workers:
                w.join(timeout=_POLL_S * 4)
        return not any(w.is_alive() for w in self._workers)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
