"""Embedding engine: a trained checkpoint behind a bucketed jitted forward.

The serving counterpart of ``eval.extract_features``: restore the encoder
from an orbax checkpoint (integrity-verified, ``utils/checkpoint.py``),
build the no-augmentation frozen forward out of
``models/contrastive.ContrastiveModel`` (float32, ``to_float`` uint8
normalization — numerically the same forward eval and save_features use),
and serve arbitrary-size request batches through a small set of static
shapes:

  * request batches are padded up to the nearest **power-of-two bucket**
    (1, 2, 4, … ``max_batch``) and sliced back after the forward, so XLA
    compiles one program per bucket instead of one per request size;
  * every bucket is **warmup-compiled at startup** (fenced with
    ``utils.profiling.synchronize`` — a value fetch, the only reliable
    completion fence on remote-tunneled runtimes), so no live request ever
    pays a compile;
  * each engine is single-device BY PLACEMENT: request batches are
    latency-bound and small, so sharding one forward buys nothing — the
    ``device`` argument pins the committed weight copy (and therefore every
    bucket program) to one chip, and scale-out is one engine per local
    device behind the shared front-end queue (``serve/replica.py``'s
    :class:`ReplicaPool`; capacity math in ``docs/SERVING.md``);
  * ``weights`` selects the resident storage format
    (:data:`~simclr_tpu.parallel.compress.WEIGHT_QUANT_MODES`): ``exact``
    keeps fp32; ``bf16`` halves it; ``int8`` stores the bucketed
    deterministic quantization from ``parallel/compress.py`` and
    dequantizes INSIDE the jitted forward, so per-replica HBM holds int8
    buckets + one fp32 scale per 1024 weights (~3.98x under fp32).

Thread model: ``embed`` is called only from one batcher worker thread (its
replica's worker under a pool); construction and warmup happen before the
worker starts.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from simclr_tpu.data.augment import to_float
from simclr_tpu.obs.compile import CompileSentry
from simclr_tpu.parallel.compress import (
    dequantize_weight_buckets,
    quantize_weight_buckets,
    validate_weight_mode,
    weight_storage_bytes,
)
from simclr_tpu.utils.fetch import fetch
from simclr_tpu.utils.profiling import synchronize


class RequestTooLargeError(ValueError):
    """A single request carries more rows than the largest bucket."""


class WeightsIncompatibleError(ValueError):
    """Staged weights do not match the resident storage layout.

    Raised by :meth:`EmbedEngine.stage_weights` when the new checkpoint's
    packed param tree differs from the committed one in structure, shape,
    or dtype — swapping it in would force a fresh XLA compile per bucket
    (or worse, run a wrong program), so the swap is refused instead.
    """


class StagedWeights:
    """A packed-and-device-resident weight set awaiting :meth:`commit`.

    Produced by :meth:`EmbedEngine.stage_weights`; carries the same pytree
    structure/shapes/dtypes as the committed storage, so the engine's
    existing bucket programs run on it without recompiling. Holding one of
    these costs a second resident weight copy on the device until it is
    committed (then the old copy is dropped) or discarded.
    """

    __slots__ = ("params", "batch_stats", "checkpoint_path")

    def __init__(self, params, batch_stats, checkpoint_path=None):
        self.params = params
        self.batch_stats = batch_stats
        self.checkpoint_path = checkpoint_path


def make_buckets(max_batch: int) -> tuple[int, ...]:
    """Power-of-two batch buckets up to ``max_batch`` (inclusive).

    A non-power-of-two ``max_batch`` contributes itself as the final bucket
    (``max_batch=24`` -> ``(1, 2, 4, 8, 16, 24)``), so the configured
    ceiling is always exactly servable.
    """
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    buckets = []
    b = 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return tuple(buckets)


class EmbedEngine:
    """Checkpoint -> warm compiled forwards -> ``embed(images)``.

    ``model`` is any flax module with the :class:`ContrastiveModel` API
    (``encode``/``__call__``, params + batch_stats); ``variables`` a host
    pytree with ``params`` and ``batch_stats``. ``use_full_encoder=False``
    serves encoder features h (the representation probes consume); True
    serves projection-head output z.
    """

    def __init__(
        self,
        model,
        variables: dict,
        *,
        max_batch: int = 256,
        use_full_encoder: bool = False,
        input_shape: tuple[int, ...] = (32, 32, 3),
        metrics=None,
        warmup: bool = True,
        sentry=None,
        device=None,
        replica_id: int | None = None,
        weights: str = "exact",
    ):
        self.model = model
        self.max_batch = int(max_batch)
        self.use_full_encoder = bool(use_full_encoder)
        self.input_shape = tuple(input_shape)
        self.buckets = make_buckets(self.max_batch)
        self.metrics = metrics
        self.device = device
        self.replica_id = replica_id
        self.weights_mode = validate_weight_mode(weights)
        # compile sentry (obs/compile.py): every bucket compilation is
        # recorded; a bucket compiled after warmup completes is the serve
        # tier's recompile alarm. A bare sentry (records only) is kept when
        # the caller has no events/telemetry to wire in. Warmup gating is
        # PER ENGINE (_warmup_done below), so under a ReplicaPool each
        # replica's own warmup never alarms even when the pool shares one
        # sentry — only a post-warmup cold bucket on that replica does.
        self.sentry = sentry if sentry is not None else CompileSentry()
        self._warmup_done = False
        self._warm: set[int] = set()
        # (name, start, end) perf_counter spans of the LAST embed() call
        # (pad + device_compute), read by the batcher's span_source. embed()
        # runs only on this engine's one batcher worker thread (see
        # embed()), so a plain attribute swap is safe.
        self.last_spans: tuple = ()
        # one committed device copy of the variables, shared by every bucket
        # program — per-request device_put of the params would dominate the
        # forward at small batches. Committing to an explicit `device` pins
        # every bucket program there (jit follows committed arguments), so
        # N engines over N devices run concurrently. The (params,
        # batch_stats) pair lives in ONE tuple attribute so hot-reload can
        # swap both atomically under concurrent embeds — a reader never
        # sees generation N params with generation N-1 batch stats.
        packed, dequant, self._n_weight_elements = self._pack_params(
            variables["params"]
        )
        self._resident = (packed, self._put(variables.get("batch_stats", {})))
        # weight-generation bookkeeping for zero-downtime hot-reload
        # (coscheduler/reload.py): 0 = construction-time variables, each
        # commit() increments. checkpoint_path names the committed source.
        self.generation = 0
        self.checkpoint_path = None

        def forward(params, batch_stats, images):
            x = to_float(images)
            vs = {"params": dequant(params), "batch_stats": batch_stats}
            if self.use_full_encoder:
                return model.apply(vs, x, train=False).astype(jnp.float32)
            return model.apply(
                vs, x, train=False, method=model.encode
            ).astype(jnp.float32)

        # jit's shape-keyed executable cache IS the bucket compile cache:
        # padding constrains every call to one of `buckets` shapes, warmup
        # populates each entry, and self._warm tracks which buckets have a
        # compiled program (the hit/miss metric).
        self._fwd = jax.jit(forward)
        if warmup:
            self.warmup()

    # -- weight storage ----------------------------------------------------
    @property
    def _params(self):
        return self._resident[0]

    @property
    def _batch_stats(self):
        return self._resident[1]

    def _put(self, tree):
        if self.device is None:
            return jax.device_put(tree)
        return jax.device_put(tree, self.device)

    def _pack_params(self, host_params):
        """Device-resident param storage per ``weights`` mode.

        Returns ``(packed, dequant, n_float_elements)`` where ``dequant``
        maps the packed storage back to the forward's fp-typed param tree
        inside the jitted program. ``int8`` quantizes the FLOAT leaves as
        one flat vector (deterministic, ``parallel/compress.py`` bucket
        format — same input, same bytes, every load and every replica) and
        carries any non-float leaf exact.
        """
        leaves, treedef = jax.tree.flatten(host_params)
        host = [np.asarray(l) for l in leaves]
        is_float = [np.issubdtype(h.dtype, np.floating) for h in host]
        n_float = int(sum(h.size for h, f in zip(host, is_float) if f))
        # exact-carried bytes (non-float param leaves) for the analytic gauge
        self._nonfloat_param_bytes = int(
            sum(h.nbytes for h, f in zip(host, is_float) if not f)
        )
        if self.weights_mode == "exact":
            return self._put(host_params), (lambda p: p), n_float
        if self.weights_mode == "bf16":
            packed = self._put(
                jax.tree.unflatten(
                    treedef,
                    [
                        h.astype(jnp.bfloat16) if f else h
                        for h, f in zip(host, is_float)
                    ],
                )
            )

            def dequant_bf16(p):
                return jax.tree.map(
                    lambda x: x.astype(jnp.float32)
                    if jnp.issubdtype(x.dtype, jnp.floating)
                    else x,
                    p,
                )

            return packed, dequant_bf16, n_float
        flat = (
            np.concatenate(
                [h.reshape(-1).astype(np.float32) for h, f in zip(host, is_float) if f]
            )
            if n_float
            else np.zeros((0,), np.float32)
        )
        q, scales = quantize_weight_buckets(flat)
        packed = self._put(
            {
                "q": q,
                "scales": scales,
                "exact": [h for h, f in zip(host, is_float) if not f],
            }
        )
        meta = [(h.shape, h.size, h.dtype) for h in host]

        def dequant_int8(p):
            vec = dequantize_weight_buckets(p["q"], p["scales"], n_float)
            out, off, exact = [], 0, iter(p["exact"])
            for (shape, size, dtype), f in zip(meta, is_float):
                if f:
                    out.append(vec[off : off + size].reshape(shape).astype(dtype))
                    off += size
                else:
                    out.append(next(exact))
            return jax.tree.unflatten(treedef, out)

        return packed, dequant_int8, n_float

    def weight_hbm_bytes(self) -> int:
        """Measured resident weight bytes on this replica's device (params
        storage + batch stats), summed from the committed arrays."""
        return int(
            sum(
                l.nbytes
                for l in jax.tree.leaves((self._params, self._batch_stats))
            )
        )

    def weight_hbm_analytic_bytes(self) -> int:
        """Analytic resident weight bytes under the storage mode:
        :func:`~simclr_tpu.parallel.compress.weight_storage_bytes` over the
        float param elements, plus the exact-carried non-float leaves and
        batch stats. Rendered next to the measured gauge so preflight and
        reality can be reconciled per replica."""
        stats_bytes = int(
            sum(l.nbytes for l in jax.tree.leaves(self._batch_stats))
        )
        return (
            weight_storage_bytes(self._n_weight_elements, self.weights_mode)
            + self._nonfloat_param_bytes
            + stats_bytes
        )

    # -- hot-reload (zero-downtime generation swap) ------------------------
    @staticmethod
    def _storage_signature(tree):
        """(treedef, [(shape, dtype)...]) of a packed tree — the identity a
        staged weight set must share with the committed one for jit's
        shape-keyed executable cache to serve it without recompiling."""
        leaves, treedef = jax.tree.flatten(tree)
        return treedef, [(tuple(l.shape), str(l.dtype)) for l in leaves]

    def stage_weights(self, variables: dict, checkpoint_path=None) -> StagedWeights:
        """Pack new checkpoint variables into a device-resident staged copy.

        Runs the SAME packing path the constructor used (so int8 staging
        yields the identical ``{"q","scales","exact"}`` layout the compiled
        forward's dequant closure expects) and verifies the packed tree is
        structure/shape/dtype-identical to the committed storage — the
        precondition for every existing bucket program to run on it with
        zero recompiles. A mismatched checkpoint (different architecture,
        head dim, weights mode artifacts) raises
        :class:`WeightsIncompatibleError` and leaves the engine untouched.

        Thread-safe against concurrent ``embed`` calls: nothing the request
        path reads is mutated until :meth:`commit`.
        """
        packed, _dequant, _n = self._pack_params(variables["params"])
        batch_stats = self._put(variables.get("batch_stats", {}))
        cur_params, cur_stats = self._resident
        if self._storage_signature(packed) != self._storage_signature(cur_params):
            raise WeightsIncompatibleError(
                "staged params storage differs from the committed layout "
                "(architecture/d/weights-mode mismatch); refusing a swap "
                "that would recompile every bucket"
            )
        if self._storage_signature(batch_stats) != self._storage_signature(
            cur_stats
        ):
            raise WeightsIncompatibleError(
                "staged batch_stats differ from the committed layout; "
                "refusing the swap"
            )
        return StagedWeights(packed, batch_stats, checkpoint_path)

    def embed_with(self, staged: StagedWeights, images: np.ndarray) -> np.ndarray:
        """Forward ``images`` through STAGED (uncommitted) weights.

        Used by the co-scheduler to re-embed the retrieval corpus with the
        incoming generation BEFORE it starts serving — the corpus swap and
        the weight swap then land back-to-back, so ``/v1/neighbors`` never
        mixes generations with ``/v1/embed``. Runs the same compiled bucket
        programs (staged storage is shape-identical by construction), and
        deliberately touches no serving metrics or spans: traffic
        accounting belongs to the committed generation.
        """
        images = np.asarray(images)
        n = images.shape[0]
        bucket = self.bucket_for(n)
        if n < bucket:
            images = np.concatenate(
                [images, np.zeros((bucket - n, *self.input_shape), np.uint8)]
            )
        out = fetch(self._fwd(staged.params, staged.batch_stats, images))
        return out[:n]

    def commit(self, staged: StagedWeights, *, generation: int | None = None):
        """Atomically swap the staged weights in as the serving generation.

        One tuple-attribute assignment: every in-flight ``embed`` finishes
        on the copy it already read, every subsequent one reads the new
        pair — zero downtime, no torn (params, batch_stats) mix. The old
        copy's device memory is released once its last reader returns.
        """
        self._resident = (staged.params, staged.batch_stats)
        self.generation = (
            self.generation + 1 if generation is None else int(generation)
        )
        if staged.checkpoint_path is not None:
            self.checkpoint_path = staged.checkpoint_path
        return self.generation

    # -- lifecycle ---------------------------------------------------------
    def warmup(self) -> dict[int, float]:
        """Compile every bucket before traffic; returns per-bucket seconds.

        Fenced with :func:`utils.profiling.synchronize` so the timing (and
        the readiness it implies) reflects finished device work, not queued
        dispatches.
        """
        times: dict[int, float] = {}
        for b in self.buckets:
            if b in self._warm:
                continue
            t0 = time.perf_counter()
            out = self._fwd(
                self._params,
                self._batch_stats,
                np.zeros((b, *self.input_shape), np.uint8),
            )
            synchronize(out)
            times[b] = time.perf_counter() - t0
            self._warm.add(b)
            self.sentry.record_compile(
                self._compile_name(b), seconds=times[b], warm=self._warmup_done
            )
        self._warmup_done = True
        return times

    def _compile_name(self, bucket: int) -> str:
        """Sentry name for a bucket compile; replica-tagged under a pool so
        fan-out keeps per-replica compile attribution distinct."""
        if self.replica_id is None:
            return f"serve_bucket_{bucket}"
        return f"serve_r{self.replica_id}_bucket_{bucket}"

    def warm_state(self) -> list[int]:
        """Buckets with a compiled program (sorted) — /healthz evidence."""
        return sorted(self._warm)

    # -- request path ------------------------------------------------------
    def bucket_for(self, n_rows: int) -> int:
        """Smallest bucket holding ``n_rows``; raises past ``max_batch``."""
        if n_rows < 1:
            raise ValueError(f"need at least one row, got {n_rows}")
        if n_rows > self.max_batch:
            raise RequestTooLargeError(
                f"{n_rows} rows exceeds serve.max_batch={self.max_batch}; "
                f"split the request"
            )
        for b in self.buckets:
            if b >= n_rows:
                return b
        raise AssertionError("unreachable: buckets end at max_batch")

    def embed(self, images: np.ndarray) -> np.ndarray:
        """Embed ``(n, *input_shape)`` uint8 rows; returns ``(n, d)`` float32.

        Pads up to the bucket, runs the warm program, slices the padding
        back off. Zero-padding is sound because the frozen forward is
        row-independent (eval-mode BN uses running statistics), so the
        padded rows cannot perturb the real ones.
        """
        images = np.asarray(images)
        if images.dtype != np.uint8:
            raise ValueError(f"images must be uint8 pixels, got {images.dtype}")
        if images.shape[1:] != self.input_shape:
            raise ValueError(
                f"images must be (n, {', '.join(map(str, self.input_shape))}), "
                f"got {images.shape}"
            )
        n = images.shape[0]
        bucket = self.bucket_for(n)
        cold = bucket not in self._warm
        if self.metrics is not None:
            if cold:
                self.metrics.compile_cache_misses_total.inc()
                if self._warmup_done:
                    self.metrics.recompile_alarms_total.inc()
            else:
                self.metrics.compile_cache_hits_total.inc()
        if cold:
            self._warm.add(bucket)
        t_pad = time.perf_counter()
        if n < bucket:
            images = np.concatenate(
                [images, np.zeros((bucket - n, *self.input_shape), np.uint8)]
            )
        t0 = time.perf_counter()
        # ONE read of the resident tuple: params and batch_stats are always
        # the same generation even if commit() swaps mid-call
        params, batch_stats = self._resident
        out = fetch(self._fwd(params, batch_stats, images))
        done = time.perf_counter()
        if cold:
            # the compiling dispatch: its duration upper-bounds the compile.
            # warm=True (post-warmup cold bucket) raises the recompile alarm.
            self.sentry.record_compile(
                self._compile_name(bucket),
                seconds=done - t0,
                warm=self._warmup_done,
            )
        # kept even for exact-bucket batches (a ~0 pad span) so every
        # request trace carries the same span shape
        self.last_spans = (("pad", t_pad, t0), ("device_compute", t0, done))
        if self.metrics is not None:
            self.metrics.batches_total.inc()
            self.metrics.batch_rows_total.inc(n)
            self.metrics.batch_capacity_total.inc(bucket)
            self.metrics.batch_latency_ms.observe((done - t0) * 1000.0)
        return out[:n]

    @property
    def feature_dim(self) -> int:
        """Output feature dimension (probed with a one-row forward)."""
        return int(
            jax.eval_shape(
                self._fwd,
                self._params,
                self._batch_stats,
                jax.ShapeDtypeStruct((1, *self.input_shape), jnp.uint8),
            ).shape[-1]
        )

    # -- construction from a run directory ---------------------------------
    @classmethod
    def from_checkpoint(
        cls,
        cfg,
        *,
        metrics=None,
        warmup: bool = True,
        sentry=None,
        device=None,
        replica_id: int | None = None,
    ):
        """Restore the newest (or explicitly chosen) checkpoint of a run.

        Uses eval's blessed constructor/loader so served embeddings are the
        same features eval and save_features compute for that checkpoint.
        Restore goes through the sha256-verified path: a truncated
        checkpoint raises before the server ever binds its port.
        """
        from simclr_tpu.eval import build_eval_model, load_model_variables
        from simclr_tpu.utils.checkpoint import latest_checkpoint

        ckpt = cfg.select("serve.checkpoint")
        if not ckpt:
            target_dir = str(cfg.experiment.target_dir)
            ckpt = latest_checkpoint(target_dir)
            if ckpt is None:
                raise FileNotFoundError(
                    f"no checkpoints found under {target_dir!r}; set "
                    f"experiment.target_dir or serve.checkpoint"
                )
        model = build_eval_model(cfg)
        variables = load_model_variables(str(ckpt))
        engine = cls(
            model,
            variables,
            max_batch=int(cfg.serve.max_batch),
            use_full_encoder=bool(cfg.parameter.use_full_encoder),
            metrics=metrics,
            warmup=warmup,
            sentry=sentry,
            device=device,
            replica_id=replica_id,
            weights=str(cfg.select("serve.weights", "exact")),
        )
        engine.checkpoint_path = str(ckpt)
        return engine
