"""Embedding engine: a trained checkpoint behind a bucketed jitted forward.

The serving counterpart of ``eval.extract_features``: restore the encoder
from an orbax checkpoint (integrity-verified, ``utils/checkpoint.py``),
build the no-augmentation frozen forward out of
``models/contrastive.ContrastiveModel`` (float32, ``to_float`` uint8
normalization — numerically the same forward eval and save_features use),
and serve arbitrary-size request batches through a small set of static
shapes:

  * request batches are padded up to the nearest **power-of-two bucket**
    (1, 2, 4, … ``max_batch``) and sliced back after the forward, so XLA
    compiles one program per bucket instead of one per request size;
  * every bucket is **warmup-compiled at startup** (fenced with
    ``utils.profiling.synchronize`` — a value fetch, the only reliable
    completion fence on remote-tunneled runtimes), so no live request ever
    pays a compile;
  * the engine is deliberately single-device (the jit default device):
    request batches are latency-bound and small, so data-parallel sharding
    buys nothing per request — scale-out is one engine process per chip
    behind a load balancer (capacity math in ``docs/SERVING.md``).

Thread model: ``embed`` is called only from the batcher's single worker
thread; construction and warmup happen before the worker starts.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from simclr_tpu.data.augment import to_float
from simclr_tpu.obs.compile import CompileSentry
from simclr_tpu.utils.fetch import fetch
from simclr_tpu.utils.profiling import synchronize


class RequestTooLargeError(ValueError):
    """A single request carries more rows than the largest bucket."""


def make_buckets(max_batch: int) -> tuple[int, ...]:
    """Power-of-two batch buckets up to ``max_batch`` (inclusive).

    A non-power-of-two ``max_batch`` contributes itself as the final bucket
    (``max_batch=24`` -> ``(1, 2, 4, 8, 16, 24)``), so the configured
    ceiling is always exactly servable.
    """
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    buckets = []
    b = 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return tuple(buckets)


class EmbedEngine:
    """Checkpoint -> warm compiled forwards -> ``embed(images)``.

    ``model`` is any flax module with the :class:`ContrastiveModel` API
    (``encode``/``__call__``, params + batch_stats); ``variables`` a host
    pytree with ``params`` and ``batch_stats``. ``use_full_encoder=False``
    serves encoder features h (the representation probes consume); True
    serves projection-head output z.
    """

    def __init__(
        self,
        model,
        variables: dict,
        *,
        max_batch: int = 256,
        use_full_encoder: bool = False,
        input_shape: tuple[int, ...] = (32, 32, 3),
        metrics=None,
        warmup: bool = True,
        sentry=None,
    ):
        self.model = model
        self.max_batch = int(max_batch)
        self.use_full_encoder = bool(use_full_encoder)
        self.input_shape = tuple(input_shape)
        self.buckets = make_buckets(self.max_batch)
        self.metrics = metrics
        # compile sentry (obs/compile.py): every bucket compilation is
        # recorded; a bucket compiled after warmup completes is the serve
        # tier's recompile alarm. A bare sentry (records only) is kept when
        # the caller has no events/telemetry to wire in.
        self.sentry = sentry if sentry is not None else CompileSentry()
        self._warmup_done = False
        self._warm: set[int] = set()
        # (name, start, end) perf_counter spans of the LAST embed() call
        # (pad + device_compute), read by the batcher's span_source. embed()
        # runs only on the batcher's single worker thread (see embed()), so
        # a plain attribute swap is safe.
        self.last_spans: tuple = ()
        # one committed device copy of the variables, shared by every bucket
        # program — per-request device_put of the params would dominate the
        # forward at small batches
        self._params = jax.device_put(variables["params"])
        self._batch_stats = jax.device_put(variables.get("batch_stats", {}))

        def forward(params, batch_stats, images):
            x = to_float(images)
            vs = {"params": params, "batch_stats": batch_stats}
            if self.use_full_encoder:
                return model.apply(vs, x, train=False).astype(jnp.float32)
            return model.apply(
                vs, x, train=False, method=model.encode
            ).astype(jnp.float32)

        # jit's shape-keyed executable cache IS the bucket compile cache:
        # padding constrains every call to one of `buckets` shapes, warmup
        # populates each entry, and self._warm tracks which buckets have a
        # compiled program (the hit/miss metric).
        self._fwd = jax.jit(forward)
        if warmup:
            self.warmup()

    # -- lifecycle ---------------------------------------------------------
    def warmup(self) -> dict[int, float]:
        """Compile every bucket before traffic; returns per-bucket seconds.

        Fenced with :func:`utils.profiling.synchronize` so the timing (and
        the readiness it implies) reflects finished device work, not queued
        dispatches.
        """
        times: dict[int, float] = {}
        for b in self.buckets:
            if b in self._warm:
                continue
            t0 = time.perf_counter()
            out = self._fwd(
                self._params,
                self._batch_stats,
                np.zeros((b, *self.input_shape), np.uint8),
            )
            synchronize(out)
            times[b] = time.perf_counter() - t0
            self._warm.add(b)
            self.sentry.record_compile(
                f"serve_bucket_{b}", seconds=times[b], warm=self._warmup_done
            )
        self._warmup_done = True
        return times

    # -- request path ------------------------------------------------------
    def bucket_for(self, n_rows: int) -> int:
        """Smallest bucket holding ``n_rows``; raises past ``max_batch``."""
        if n_rows < 1:
            raise ValueError(f"need at least one row, got {n_rows}")
        if n_rows > self.max_batch:
            raise RequestTooLargeError(
                f"{n_rows} rows exceeds serve.max_batch={self.max_batch}; "
                f"split the request"
            )
        for b in self.buckets:
            if b >= n_rows:
                return b
        raise AssertionError("unreachable: buckets end at max_batch")

    def embed(self, images: np.ndarray) -> np.ndarray:
        """Embed ``(n, *input_shape)`` uint8 rows; returns ``(n, d)`` float32.

        Pads up to the bucket, runs the warm program, slices the padding
        back off. Zero-padding is sound because the frozen forward is
        row-independent (eval-mode BN uses running statistics), so the
        padded rows cannot perturb the real ones.
        """
        images = np.asarray(images)
        if images.dtype != np.uint8:
            raise ValueError(f"images must be uint8 pixels, got {images.dtype}")
        if images.shape[1:] != self.input_shape:
            raise ValueError(
                f"images must be (n, {', '.join(map(str, self.input_shape))}), "
                f"got {images.shape}"
            )
        n = images.shape[0]
        bucket = self.bucket_for(n)
        cold = bucket not in self._warm
        if self.metrics is not None:
            if cold:
                self.metrics.compile_cache_misses_total.inc()
                if self._warmup_done:
                    self.metrics.recompile_alarms_total.inc()
            else:
                self.metrics.compile_cache_hits_total.inc()
        if cold:
            self._warm.add(bucket)
        t_pad = time.perf_counter()
        if n < bucket:
            images = np.concatenate(
                [images, np.zeros((bucket - n, *self.input_shape), np.uint8)]
            )
        t0 = time.perf_counter()
        out = fetch(self._fwd(self._params, self._batch_stats, images))
        done = time.perf_counter()
        if cold:
            # the compiling dispatch: its duration upper-bounds the compile.
            # warm=True (post-warmup cold bucket) raises the recompile alarm.
            self.sentry.record_compile(
                f"serve_bucket_{bucket}",
                seconds=done - t0,
                warm=self._warmup_done,
            )
        # kept even for exact-bucket batches (a ~0 pad span) so every
        # request trace carries the same span shape
        self.last_spans = (("pad", t_pad, t0), ("device_compute", t0, done))
        if self.metrics is not None:
            self.metrics.batches_total.inc()
            self.metrics.batch_rows_total.inc(n)
            self.metrics.batch_capacity_total.inc(bucket)
            self.metrics.batch_latency_ms.observe((done - t0) * 1000.0)
        return out[:n]

    @property
    def feature_dim(self) -> int:
        """Output feature dimension (probed with a one-row forward)."""
        return int(
            jax.eval_shape(
                self._fwd,
                self._params,
                self._batch_stats,
                jax.ShapeDtypeStruct((1, *self.input_shape), jnp.uint8),
            ).shape[-1]
        )

    # -- construction from a run directory ---------------------------------
    @classmethod
    def from_checkpoint(cls, cfg, *, metrics=None, warmup: bool = True, sentry=None):
        """Restore the newest (or explicitly chosen) checkpoint of a run.

        Uses eval's blessed constructor/loader so served embeddings are the
        same features eval and save_features compute for that checkpoint.
        Restore goes through the sha256-verified path: a truncated
        checkpoint raises before the server ever binds its port.
        """
        from simclr_tpu.eval import build_eval_model, load_model_variables
        from simclr_tpu.utils.checkpoint import latest_checkpoint

        ckpt = cfg.select("serve.checkpoint")
        if not ckpt:
            target_dir = str(cfg.experiment.target_dir)
            ckpt = latest_checkpoint(target_dir)
            if ckpt is None:
                raise FileNotFoundError(
                    f"no checkpoints found under {target_dir!r}; set "
                    f"experiment.target_dir or serve.checkpoint"
                )
        model = build_eval_model(cfg)
        variables = load_model_variables(str(ckpt))
        engine = cls(
            model,
            variables,
            max_batch=int(cfg.serve.max_batch),
            use_full_encoder=bool(cfg.parameter.use_full_encoder),
            metrics=metrics,
            warmup=warmup,
            sentry=sentry,
        )
        engine.checkpoint_path = str(ckpt)
        return engine
