"""On-device top-k retrieval over a row-sharded embedding corpus.

The similarity-search half of the serve tier (``POST /v1/neighbors``). The
corpus — an ``(n, d)`` float32 embedding matrix, typically produced by
``eval.save_features`` — is sharded ONCE onto a data-axis-only mesh over
every local device, so per-chip HBM holds ``~n/S`` rows and the corpus can
grow with the slice. Queries are answered entirely on device. The DEFAULT
path is exact brute-force and unchanged:

  * each shard computes its local score block ``q @ shard.T`` (B x R) and
    keeps only its local ``top_k`` — the full B x n similarity matrix is
    never materialized anywhere, host or device;
  * the ``min(k, R)`` local winners per shard (scores + GLOBAL row ids,
    padding rows masked to -inf) are ``all_gather``ed and merged with one
    final ``top_k`` over the ``S * min(k, R)`` candidates. ``min(k, R)``
    per shard is sufficient for exactness: no shard can place more than
    ``R`` rows in the global top-k.
  * the merge is **oracle-exact including ties**: XLA's TopK is stable
    (equal scores -> lowest index first), and candidates are laid out
    shard-major, so the global tie-break is lowest global row id — exactly
    ``np.argsort(-scores, kind="stable")`` (pinned by test).

Two orthogonal scaling knobs change what each shard SCORES, not how the
winners merge (all four mode combinations share the gather/merge tail):

  * ``serve.corpus_dtype=int8`` stores each shard's ``(R*d,)`` row block in
    ``compress.py``'s deterministic bucketed int8 format (one fp32 scale
    per 1024 elements, round-to-nearest) and dequantizes INSIDE the jitted
    kernel — ~3.98x more rows per device at the same HBM, still scoring
    every row (only the stored corpus is quantized; scores are fp32).
  * ``serve.ann_cells > 0`` turns on a two-stage IVF scan: at load each
    shard k-means-clusters its own row block (``eval.kmeans`` — the
    centroid-probe machinery reused as a coarse quantizer) into ``C`` cells
    stored as padded ``(C, L, d)`` tiles; at query time each query routes to
    its ``ann_probe`` nearest cells (``argmax(q·c - ||c||²/2)``) and scores
    only those tiles — ``probe/cells`` of the exact FLOPs and bytes. Because
    every row lives in exactly one cell, ``ann_probe == ann_cells`` scores
    the full shard and the candidate set equals the exact path's (recall
    1.0, pinned by test); recall is monotone in ``ann_probe`` since the
    candidate sets nest.

Query batches are padded to the same power-of-two buckets the embed path
uses (one compiled program per (k, bucket), warmed lazily); compiles are
recorded to the CompileSentry with ``warm=False`` so a novel ``k`` never
trips the serve recompile alarm, which guards the *embed* warmup contract.

:class:`MutableCorpus` makes the corpus a live, writable store: upserts and
deletes (``POST /v1/corpus/{upsert,delete}``) rebuild a fresh generation-
tagged :class:`NeighborIndex` off to the side and commit it with one atomic
reference swap (the same stage-then-commit discipline as the coscheduler's
``ReloadManager``) — in-flight queries keep the index they started with, so
a mutation can never serve a torn shard.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from simclr_tpu.parallel.compress import (
    DEFAULT_BUCKET_SIZE,
    dequantize_weight_buckets,
    quantize_weight_buckets,
    validate_corpus_dtype,
)
from simclr_tpu.parallel.mesh import (
    DATA_AXIS,
    batch_sharding,
    retrieval_mesh,
    shard_map,
)
from simclr_tpu.serve.engine import make_buckets
from simclr_tpu.utils.fetch import fetch

METRICS = ("dot", "cosine")

# routing score for a padding centroid (shards with fewer real rows than
# cells): the -||c||²/2 term makes a huge-norm centroid unroutable without
# ever producing a non-finite value inside the kernel
_PAD_CENTROID = 1.0e4


def _normalize_rows(x: np.ndarray) -> np.ndarray:
    norms = np.linalg.norm(x, axis=1, keepdims=True)
    return x / np.where(norms > 0.0, norms, 1.0)


def _balanced_assign(block: np.ndarray, cent: np.ndarray, cap: int) -> np.ndarray:
    """Capacity-capped nearest-centroid assignment for IVF tile packing.

    Rows claim their best cell (k-means rule: argmax of ``x·c - ||c||²/2``)
    in confidence order; a full cell spills the row to its next-best cell
    with space. Total capacity ``cells * cap >= len(block)`` is guaranteed
    by the caller's tile sizing, so every row lands somewhere and the
    probe == cells candidate set still covers the whole shard.
    """
    logits = block @ cent.T - 0.5 * np.sum(cent * cent, axis=1)[None, :]
    ranked = np.argsort(-logits, axis=1)
    order = np.argsort(-np.max(logits, axis=1))
    counts = np.zeros(cent.shape[0], np.int64)
    assign = np.empty(block.shape[0], np.int32)
    for i in order:
        for c in ranked[i]:
            if counts[c] < cap:
                assign[i] = c
                counts[c] += 1
                break
    return assign


def _load_corpus(path: str):
    """Host array from ``.npy``/``.npz`` (``eval.save_features`` layout).

    ``.npy`` opens as ``mmap_mode="r"`` — :class:`NeighborIndex` slices one
    shard's row block at a time off the map, so a multi-GiB corpus is never
    duplicated in host RAM on the way to HBM. ``.npz`` is zip-compressed
    (not mappable): the named array decompresses fully, as before.
    """
    path = str(path)
    if path.endswith(".npz"):
        with np.load(path) as z:
            key = "features" if "features" in z.files else z.files[0]
            return z[key]
    return np.load(path, mmap_mode="r")


def _merge_local_topk(q, vals, gidx, k: int):
    """Shared merge tail: per-shard (B, kk) winners -> global (B, k).

    (S, B, kk) -> shard-major (B, S*kk) candidate lists: stable TopK over
    this layout tie-breaks to the lowest global row id.
    """
    vals_all = jax.lax.all_gather(vals, DATA_AXIS)
    gidx_all = jax.lax.all_gather(gidx, DATA_AXIS)
    cand_vals = jnp.moveaxis(vals_all, 0, 1).reshape(q.shape[0], -1)
    cand_idx = jnp.moveaxis(gidx_all, 0, 1).reshape(q.shape[0], -1)
    top_vals, pos = jax.lax.top_k(cand_vals, k)
    top_idx = jnp.take_along_axis(cand_idx, pos, axis=1)
    return top_vals, top_idx


class NeighborIndex:
    """Row-sharded corpus + per-(k, bucket) compiled top-k programs.

    ``metric="cosine"`` L2-normalizes corpus rows at upload and queries at
    request time, reducing cosine similarity to the same dot-product
    kernel. ``corpus_dtype`` picks the resident storage format and
    ``ann_cells``/``ann_probe`` the scan strategy (module docstring); the
    defaults — fp32, exact — are byte-identical to the original index.
    Thread model: ``query`` may be called from any handler thread; a lock
    serializes program build + compile bookkeeping (the matmul itself is
    serialized by jax's dispatch anyway).
    """

    def __init__(
        self,
        corpus,
        *,
        metric: str = "dot",
        max_queries: int = 256,
        mesh=None,
        sentry=None,
        metrics=None,
        generation: int = 0,
        corpus_dtype: str = "fp32",
        ann_cells: int = 0,
        ann_probe: int = 1,
        row_ids=None,
    ):
        if metric not in METRICS:
            raise ValueError(f"neighbors metric must be one of {METRICS}, got {metric!r}")
        validate_corpus_dtype(corpus_dtype)
        if int(ann_cells) < 0:
            raise ValueError(f"ann_cells must be >= 0 (0 = exact scan), got {ann_cells}")
        if int(ann_probe) < 1:
            raise ValueError(f"ann_probe must be >= 1, got {ann_probe}")
        # keep ndarrays (incl. np.memmap) by reference: shard blocks are
        # sliced off lazily so a memmapped corpus never fully materializes
        host = corpus if isinstance(corpus, np.ndarray) else np.asarray(corpus, np.float32)
        if host.ndim != 2 or host.shape[0] < 1:
            raise ValueError(f"corpus must be (n >= 1, d), got {host.shape}")
        self.metric = metric
        self.dtype = corpus_dtype
        # which encoder generation embedded this corpus (coscheduler swap /
        # corpus-mutation tag): a fresh index is built per swap and the
        # server's index reference swapped atomically, so /v1/neighbors
        # always answers from one coherent (weights, corpus) generation
        self.generation = int(generation)
        self.n, self.d = host.shape
        if row_ids is not None:
            row_ids = np.asarray(row_ids, np.int64).reshape(-1)
            if row_ids.shape[0] != self.n:
                raise ValueError(
                    f"row_ids must have one id per corpus row ({self.n}), "
                    f"got {row_ids.shape[0]}"
                )
        # external ids for the rows (MutableCorpus); None = positions are ids
        self.row_ids = row_ids
        self.mesh = mesh if mesh is not None else retrieval_mesh()
        self.n_shards = self.mesh.shape[DATA_AXIS]
        self.rows_per_shard = -(-self.n // self.n_shards)
        self.max_queries = int(max_queries)
        self.buckets = make_buckets(self.max_queries)
        self.sentry = sentry
        self.metrics = metrics
        self._lock = threading.Lock()
        self._fns: dict[int, object] = {}
        self._compiled: set[tuple[int, int]] = set()
        self._build_device_state(host, int(ann_cells), int(ann_probe))
        if metrics is not None:
            hbm = sum(int(a.nbytes) for a in self._device_arrays)
            if hasattr(metrics, "corpus_hbm_bytes"):
                metrics.corpus_hbm_bytes.set(hbm)
            if hasattr(metrics, "corpus_rows"):
                metrics.corpus_rows.set(self.n)
            if hasattr(metrics, "ann_cells_probed"):
                metrics.ann_cells_probed.set(self.ann_probe if self.ann_cells else 0)

    # -- corpus residency ---------------------------------------------------
    def _shard_block(self, host, s: int) -> np.ndarray:
        """Shard ``s``'s padded (R, d) fp32 row block, sliced from ``host``.

        Materializes ONE shard's rows (fp32-converts + normalizes just that
        slice) — with a memmapped ``host`` this is the only host copy that
        ever exists, which is the point of ``from_file``'s ``mmap_mode``.
        """
        r = self.rows_per_shard
        start, stop = s * r, min((s + 1) * r, self.n)
        x = np.asarray(host[start:stop], np.float32)
        if self.metric == "cosine":
            x = _normalize_rows(x)
        if stop - start < r:
            pad = np.zeros((r - max(stop - start, 0), self.d), np.float32)
            x = np.concatenate([x, pad]) if x.size else pad
        return x

    def _build_device_state(self, host, ann_cells: int, ann_probe: int) -> None:
        """Build the mode's device-resident arrays, one shard at a time."""
        s_count, r, d = self.n_shards, self.rows_per_shard, self.d
        shard0 = NamedSharding(self.mesh, P(DATA_AXIS))
        self.ann_cells = 0
        self.ann_probe = 0
        self.cell_rows = 0
        self.corpus = None

        if not ann_cells:
            if self.dtype == "fp32":
                # device-resident, row-sharded over the data axis; the padded
                # tail is masked to -inf in the kernel so it can never win
                self.corpus = jax.make_array_from_callback(
                    (s_count * r, d),
                    batch_sharding(self.mesh),
                    lambda idx: self._shard_block(host, (idx[0].start or 0) // r),
                )
                self._device_arrays = (self.corpus,)
                self._operands = (self.corpus,)
            else:
                nb = -(-(r * d) // DEFAULT_BUCKET_SIZE) if r * d else 1
                cache: dict[int, tuple[np.ndarray, np.ndarray]] = {}

                def qblock(s):
                    if s not in cache:
                        cache[s] = quantize_weight_buckets(
                            self._shard_block(host, s).reshape(-1)
                        )
                    return cache[s]

                q8 = jax.make_array_from_callback(
                    (s_count, nb, DEFAULT_BUCKET_SIZE),
                    shard0,
                    lambda idx: qblock(idx[0].start or 0)[0][None],
                )
                sc = jax.make_array_from_callback(
                    (s_count, nb),
                    shard0,
                    lambda idx: qblock(idx[0].start or 0)[1][None],
                )
                self._device_arrays = (q8, sc)
                self._operands = (q8, sc)
            return

        # IVF: per-shard k-means — each shard clusters its own row block, so
        # the FLOP savings stay local and the exact path's gather/merge tail
        # is reused unchanged (probe == cells scores exactly the exact
        # path's candidate set)
        from simclr_tpu.eval import kmeans  # lazy: pulls in the eval stack

        cells = max(1, min(ann_cells, r))
        # Balanced tiles: every cell is capped at ``tile`` rows (mean
        # occupancy + 25% slack, rounded to a multiple of 8), and rows that
        # overflow their nearest cell spill to the next-nearest with space.
        # Without the cap one skewed k-means cell sets the shared tile
        # length for ALL cells, ballooning both the padded HBM footprint
        # and the per-query candidate set (probe * tile) by the skew factor.
        cap = -(-r // cells)
        tile = max(1, min(r, ((cap + (cap + 3) // 4) + 7) // 8 * 8))
        cents, assigns = [], []
        for s in range(s_count):
            real = max(0, min(self.n - s * r, r))
            block = self._shard_block(host, s)[:real]
            if real:
                c_s, _ = kmeans(block, cells, seed=0)
                a_s = _balanced_assign(block, c_s, tile)
            else:
                c_s, a_s = np.zeros((0, d), np.float32), np.zeros((0,), np.int32)
            if c_s.shape[0] < cells:
                # pad with unroutable centroids (huge norm loses the
                # -||c||²/2 routing race); their cells hold only padding ids
                pad = np.full((cells - c_s.shape[0], d), _PAD_CENTROID, np.float32)
                c_s = np.concatenate([c_s, pad]) if c_s.size else pad
            cents.append(c_s)
            assigns.append(a_s)
        self.ann_cells = cells
        self.ann_probe = min(ann_probe, cells)
        self.cell_rows = tile

        tile_cache: dict[int, tuple[np.ndarray, np.ndarray]] = {}

        def tiles_for(s):
            if s not in tile_cache:
                block = self._shard_block(host, s)
                a_s = assigns[s]
                ids = np.full((cells, tile), -1, np.int32)
                rows = np.zeros((cells, tile, d), np.float32)
                for c in range(cells):
                    pos = np.nonzero(a_s == c)[0]
                    ids[c, : len(pos)] = s * r + pos
                    rows[c, : len(pos)] = block[pos]
                tile_cache[s] = (ids, rows)
            return tile_cache[s]

        cent = jax.make_array_from_callback(
            (s_count, cells, d), shard0, lambda idx: cents[idx[0].start or 0][None]
        )
        cell_ids = jax.make_array_from_callback(
            (s_count, cells, tile),
            shard0,
            lambda idx: tiles_for(idx[0].start or 0)[0][None],
        )
        if self.dtype == "fp32":
            tiles = jax.make_array_from_callback(
                (s_count, cells, tile, d),
                shard0,
                lambda idx: tiles_for(idx[0].start or 0)[1][None],
            )
            self._device_arrays = (cent, cell_ids, tiles)
            self._operands = (cent, cell_ids, tiles)
        else:
            nbc = -(-(tile * d) // DEFAULT_BUCKET_SIZE)
            quant_cache: dict[int, tuple[np.ndarray, np.ndarray]] = {}

            def qtiles_for(s):
                if s not in quant_cache:
                    rows = tiles_for(s)[1]
                    qs = [quantize_weight_buckets(rows[c].reshape(-1)) for c in range(cells)]
                    quant_cache[s] = (
                        np.stack([q for q, _ in qs]),
                        np.stack([sc for _, sc in qs]),
                    )
                return quant_cache[s]

            tiles_q = jax.make_array_from_callback(
                (s_count, cells, nbc, DEFAULT_BUCKET_SIZE),
                shard0,
                lambda idx: qtiles_for(idx[0].start or 0)[0][None],
            )
            tiles_s = jax.make_array_from_callback(
                (s_count, cells, nbc),
                shard0,
                lambda idx: qtiles_for(idx[0].start or 0)[1][None],
            )
            self._device_arrays = (cent, cell_ids, tiles_q, tiles_s)
            self._operands = (cent, cell_ids, tiles_q, tiles_s)

    @classmethod
    def from_file(cls, path: str, **kwargs):
        """Load an ``(n, d)`` corpus from ``.npy`` (memmapped — never doubles
        host RAM) or ``.npz`` (first array, or the ``features`` key)."""
        return cls(_load_corpus(path), **kwargs)

    # -- program construction ----------------------------------------------
    def _fn_for(self, k: int):
        """The jitted shard_map top-k program for one ``k`` (shape-keyed jit
        cache handles the query buckets)."""
        fn = self._fns.get(k)
        if fn is not None:
            return fn
        n, r, d = self.n, self.rows_per_shard, self.d

        if not self.ann_cells:
            kk = min(k, r)
            if self.dtype == "fp32":

                def local_merge(q, shard):
                    # q: (B, d) replicated; shard: (R, d) this shard's rows
                    scores = q @ shard.T  # the only similarity block ever built
                    sidx = jax.lax.axis_index(DATA_AXIS)
                    global_idx = sidx * r + jnp.arange(r, dtype=jnp.int32)
                    scores = jnp.where(global_idx[None, :] < n, scores, -jnp.inf)
                    vals, idx = jax.lax.top_k(scores, kk)
                    gidx = jnp.take(global_idx, idx)
                    return _merge_local_topk(q, vals, gidx, k)

                n_operands = 1
            else:

                def local_merge(q, q8, sc):
                    # HBM holds int8 buckets + scales; the fp32 shard exists
                    # transiently inside this program only
                    shard = dequantize_weight_buckets(q8[0], sc[0], r * d).reshape(r, d)
                    scores = q @ shard.T
                    sidx = jax.lax.axis_index(DATA_AXIS)
                    global_idx = sidx * r + jnp.arange(r, dtype=jnp.int32)
                    scores = jnp.where(global_idx[None, :] < n, scores, -jnp.inf)
                    vals, idx = jax.lax.top_k(scores, kk)
                    gidx = jnp.take(global_idx, idx)
                    return _merge_local_topk(q, vals, gidx, k)

                n_operands = 2
        else:
            p, tile = self.ann_probe, self.cell_rows
            m = p * tile
            kk = min(k, m)

            def route(q, cent):
                # nearest-centroid routing: argmax(q·c - ||c||²/2) — the
                # k-means assignment rule, so queries land in the cells
                # their neighbors were binned into
                cs = q @ cent.T - 0.5 * jnp.sum(cent * cent, axis=1)[None, :]
                _, cell_idx = jax.lax.top_k(cs, p)
                return cell_idx  # (B, p)

            if self.dtype == "fp32":

                def local_merge(q, cent, ids, tiles):
                    b = q.shape[0]
                    cell_idx = route(q, cent[0])
                    # (B, m, d) candidate block scored as a batched matvec —
                    # the 4-d einsum form lowers to scalar loops on CPU
                    t = tiles[0][cell_idx].reshape(b, m, d)
                    gid = ids[0][cell_idx].reshape(b, m)
                    scores = jax.lax.dot_general(
                        t, q, (((2,), (1,)), ((0,), (0,)))
                    )
                    scores = jnp.where(gid >= 0, scores, -jnp.inf)
                    vals, idx = jax.lax.top_k(scores, kk)
                    gidx = jnp.take_along_axis(gid, idx, axis=1)
                    return _merge_local_topk(q, vals, gidx, k)

                n_operands = 3
            else:

                def local_merge(q, cent, ids, tq, ts):
                    b = q.shape[0]
                    cell_idx = route(q, cent[0])
                    # gather stays int8 — only the probed tiles dequantize
                    x = tq[0][cell_idx].astype(jnp.float32) * ts[0][cell_idx][..., None]
                    t = x.reshape(b, p, -1)[:, :, : tile * d].reshape(b, m, d)
                    gid = ids[0][cell_idx].reshape(b, m)
                    scores = jax.lax.dot_general(
                        t, q, (((2,), (1,)), ((0,), (0,)))
                    )
                    scores = jnp.where(gid >= 0, scores, -jnp.inf)
                    vals, idx = jax.lax.top_k(scores, kk)
                    gidx = jnp.take_along_axis(gid, idx, axis=1)
                    return _merge_local_topk(q, vals, gidx, k)

                n_operands = 4

        fn = jax.jit(
            shard_map(
                local_merge,
                mesh=self.mesh,
                in_specs=(P(),) + (P(DATA_AXIS),) * n_operands,
                out_specs=(P(), P()),
                check_vma=False,
            )
        )
        self._fns[k] = fn
        return fn

    def bucket_for(self, n_queries: int) -> int:
        if n_queries < 1:
            raise ValueError(f"need at least one query, got {n_queries}")
        if n_queries > self.max_queries:
            raise ValueError(
                f"{n_queries} queries exceeds the {self.max_queries}-query "
                f"ceiling; split the request"
            )
        for b in self.buckets:
            if b >= n_queries:
                return b
        raise AssertionError("unreachable: buckets end at max_queries")

    def warmup(self, k: int) -> None:
        """Pre-compile every query bucket for one ``k`` (served cold
        otherwise — neighbors compiles never alarm)."""
        for b in self.buckets:
            self._query_padded(np.zeros((b, self.d), np.float32), k, b)

    # -- request path ------------------------------------------------------
    def query(self, queries, k: int) -> tuple[np.ndarray, np.ndarray]:
        """Top-``k`` over the corpus; ``(B, k)`` scores + row indices.

        ``queries``: ``(B, d)`` float rows. ``k`` must fit the corpus
        (``1 <= k <= n``); under ANN it must also fit the probed candidate
        set. Exact modes fill every slot with a real row; ANN slots beyond
        the probed cells' real rows come back as index -1 / score -inf.
        """
        q = np.asarray(queries, np.float32)
        if q.ndim != 2 or q.shape[1] != self.d:
            raise ValueError(f"queries must be (B, {self.d}), got {q.shape}")
        if not 1 <= int(k) <= self.n:
            raise ValueError(f"k must be in [1, {self.n}] for a {self.n}-row corpus, got {k}")
        k = int(k)
        if self.ann_cells:
            cand = self.n_shards * self.ann_probe * self.cell_rows
            if k > cand:
                raise ValueError(
                    f"k={k} exceeds the {cand} candidates reachable at "
                    f"ann_probe={self.ann_probe} (raise serve.ann_probe)"
                )
        b = q.shape[0]
        bucket = self.bucket_for(b)
        if self.metric == "cosine":
            q = _normalize_rows(q)
        if b < bucket:
            q = np.concatenate([q, np.zeros((bucket - b, self.d), np.float32)])
        t0 = time.perf_counter()
        vals, idx = self._query_padded(q, k, bucket)
        if self.metrics is not None:
            self.metrics.neighbors_requests_total.inc()
            self.metrics.neighbors_queries_total.inc(b)
            self.metrics.neighbors_latency_ms.observe(
                (time.perf_counter() - t0) * 1000.0
            )
        return np.asarray(vals[:b]), np.asarray(idx[:b], np.int64)

    def _query_padded(self, q: np.ndarray, k: int, bucket: int):
        with self._lock:
            fn = self._fn_for(k)
            cold = (k, bucket) not in self._compiled
            if cold:
                self._compiled.add((k, bucket))
        t0 = time.perf_counter()
        out_vals, out_idx = fn(q, *self._operands)
        vals, idx = fetch(out_vals), fetch(out_idx)
        if cold and self.sentry is not None:
            # warm=False by design: novel (k, bucket) programs are an
            # expected lazy compile, not a broken embed warmup
            self.sentry.record_compile(
                f"neighbors_k{k}_q{bucket}",
                seconds=time.perf_counter() - t0,
                warm=False,
            )
        return vals, idx

    # -- observability ------------------------------------------------------
    def hbm_state(self) -> dict:
        """The /healthz ``neighbors`` entry: corpus residency + programs."""
        return {
            "rows": self.n,
            "dim": self.d,
            "metric": self.metric,
            "corpus_dtype": self.dtype,
            "ann_cells": self.ann_cells,
            "ann_probe": self.ann_probe,
            "cell_rows": self.cell_rows,
            "generation": self.generation,
            "shards": self.n_shards,
            "rows_per_shard": self.rows_per_shard,
            "corpus_hbm_bytes": sum(int(a.nbytes) for a in self._device_arrays),
            "compiled_programs": sorted(self._compiled),
        }


class MutableCorpus:
    """Generation-tagged mutable corpus: the store behind ``/v1/corpus/*``.

    Holds the authoritative host rows + external int64 ids and rebuilds a
    fresh :class:`NeighborIndex` per mutation, committing it to the server
    with one atomic reference swap INSIDE the mutation lock — concurrent
    mutations therefore commit in generation order, and a reader either
    sees the old complete index or the new complete index, never a torn
    mix (handlers read ``server.index`` once per request). ``index_kwargs``
    (metric, dtype, ANN knobs, mesh, sentry, max_queries) are captured at
    construction and reused for every rebuild.

    A memmapped ``embeddings`` (the ``from_file`` path) stays on the map
    until the first mutation, which materializes a private fp32 copy.
    """

    def __init__(
        self,
        embeddings,
        *,
        ids=None,
        server=None,
        metrics=None,
        generation: int = 0,
        **index_kwargs,
    ):
        rows = embeddings if isinstance(embeddings, np.ndarray) else np.asarray(
            embeddings, np.float32
        )
        if rows.ndim != 2 or rows.shape[0] < 1:
            raise ValueError(f"corpus must be (n >= 1, d), got {rows.shape}")
        n = rows.shape[0]
        if ids is None:
            id_arr = np.arange(n, dtype=np.int64)
        else:
            id_arr = np.asarray(ids, np.int64).reshape(-1)
            if id_arr.shape[0] != n:
                raise ValueError(f"need one id per row ({n}), got {id_arr.shape[0]}")
            if np.unique(id_arr).shape[0] != n:
                raise ValueError("corpus ids must be unique")
        self._rows = rows
        self._ids = id_arr
        self.server = server
        self.metrics = metrics
        self._kwargs = dict(index_kwargs)
        self.lock = threading.Lock()
        self.generation = int(generation)
        self.index = None
        with self.lock:
            self._commit(self.generation)

    @classmethod
    def from_file(cls, path: str, **kwargs):
        """Memmap-backed store from ``.npy``/``.npz`` (same loader as
        :meth:`NeighborIndex.from_file`)."""
        return cls(_load_corpus(path), **kwargs)

    @property
    def rows(self) -> int:
        return self._rows.shape[0]

    def _commit(self, generation: int) -> None:
        """Build + publish one generation. Caller holds ``self.lock``: the
        swap happens inside the mutation critical section so generations
        can only ever become visible in the order they were built."""
        index = NeighborIndex(
            self._rows,
            metrics=self.metrics,
            generation=int(generation),
            row_ids=self._ids.copy(),
            **self._kwargs,
        )
        self.index = index
        self.generation = int(generation)
        if self.server is not None:
            self.server.swap_index(index)
        if self.metrics is not None and hasattr(self.metrics, "corpus_generation"):
            self.metrics.corpus_generation.set(self.generation)

    def _materialized(self) -> np.ndarray:
        """A private writable fp32 copy of the rows (mutations never write
        through to a caller's array or a read-only memmap)."""
        return np.array(self._rows, np.float32)

    def upsert(self, ids, embeddings) -> dict:
        """Insert-or-update rows by external id; returns the new state."""
        id_arr = np.asarray(ids, np.int64).reshape(-1)
        emb = np.asarray(embeddings, np.float32)
        if emb.ndim != 2 or emb.shape[0] != id_arr.shape[0]:
            raise ValueError(
                f"embeddings must be ({id_arr.shape[0]}, d) — one row per id — "
                f"got {emb.shape}"
            )
        if np.unique(id_arr).shape[0] != id_arr.shape[0]:
            raise ValueError("upsert ids must be unique within one request")
        with self.lock:
            if emb.shape[1] != self._rows.shape[1]:
                raise ValueError(
                    f"embedding dim {emb.shape[1]} != corpus dim {self._rows.shape[1]}"
                )
            pos = {int(v): i for i, v in enumerate(self._ids)}
            rows = self._materialized()
            fresh = [i for i, v in enumerate(id_arr) if int(v) not in pos]
            for i, v in enumerate(id_arr):
                p = pos.get(int(v))
                if p is not None:
                    rows[p] = emb[i]
            if fresh:
                rows = np.concatenate([rows, emb[fresh]])
                self._ids = np.concatenate([self._ids, id_arr[fresh]])
            self._rows = rows
            self._commit(self.generation + 1)
            return {"generation": self.generation, "rows": self.rows}

    def delete(self, ids) -> dict:
        """Remove rows by external id; unknown ids are an error (a delete
        that silently no-ops would mask producer/consumer id drift)."""
        id_arr = np.asarray(ids, np.int64).reshape(-1)
        if id_arr.shape[0] < 1:
            raise ValueError("delete needs at least one id")
        with self.lock:
            known = set(int(v) for v in self._ids)
            missing = sorted(int(v) for v in id_arr if int(v) not in known)
            if missing:
                raise ValueError(f"unknown corpus ids: {missing[:8]}")
            drop = set(int(v) for v in id_arr)
            keep = np.array([int(v) not in drop for v in self._ids], bool)
            if not keep.any():
                raise ValueError(
                    "cannot delete every corpus row (the index needs n >= 1)"
                )
            self._rows = self._materialized()[keep]
            self._ids = self._ids[keep]
            self._commit(self.generation + 1)
            return {"generation": self.generation, "rows": self.rows}

    def replace(self, embeddings, generation: int) -> dict:
        """Wholesale corpus swap — the coscheduler's per-weight-swap re-embed
        path. Ids reset to row positions; the committed generation is the
        caller's tag unless interleaved mutations already advanced past it
        (``max`` keeps the sequence monotone either way)."""
        rows = embeddings if isinstance(embeddings, np.ndarray) else np.asarray(
            embeddings, np.float32
        )
        if rows.ndim != 2 or rows.shape[0] < 1:
            raise ValueError(f"corpus must be (n >= 1, d), got {rows.shape}")
        with self.lock:
            self._rows = rows
            self._ids = np.arange(rows.shape[0], dtype=np.int64)
            self._commit(max(int(generation), self.generation + 1))
            return {"generation": self.generation, "rows": self.rows}
