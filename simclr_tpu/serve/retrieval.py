"""On-device exact top-k retrieval over a row-sharded embedding corpus.

The similarity-search half of the serve tier (``POST /v1/neighbors``). The
corpus — an ``(n, d)`` float32 embedding matrix, typically produced by
``eval.save_features`` — is uploaded ONCE through the training stack's
``parallel.mesh.put_row_sharded`` onto a data-axis-only mesh over every
local device, so per-chip HBM holds ``~n/S`` rows and the corpus can grow
with the slice. Queries are answered entirely on device:

  * each shard computes its local score block ``q @ shard.T`` (B x R) and
    keeps only its local ``top_k`` — the full B x n similarity matrix is
    never materialized anywhere, host or device;
  * the ``min(k, R)`` local winners per shard (scores + GLOBAL row ids,
    padding rows masked to -inf) are ``all_gather``ed and merged with one
    final ``top_k`` over the ``S * min(k, R)`` candidates. ``min(k, R)``
    per shard is sufficient for exactness: no shard can place more than
    ``R`` rows in the global top-k.
  * the merge is **oracle-exact including ties**: XLA's TopK is stable
    (equal scores -> lowest index first), and candidates are laid out
    shard-major, so the global tie-break is lowest global row id — exactly
    ``np.argsort(-scores, kind="stable")`` (pinned by test).

Query batches are padded to the same power-of-two buckets the embed path
uses (one compiled program per (k, bucket), warmed lazily); compiles are
recorded to the CompileSentry with ``warm=False`` so a novel ``k`` never
trips the serve recompile alarm, which guards the *embed* warmup contract.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from simclr_tpu.parallel.mesh import (
    DATA_AXIS,
    put_row_sharded,
    retrieval_mesh,
    shard_map,
)
from simclr_tpu.serve.engine import make_buckets
from simclr_tpu.utils.fetch import fetch

METRICS = ("dot", "cosine")


def _normalize_rows(x: np.ndarray) -> np.ndarray:
    norms = np.linalg.norm(x, axis=1, keepdims=True)
    return x / np.where(norms > 0.0, norms, 1.0)


class NeighborIndex:
    """Row-sharded corpus + per-(k, bucket) compiled exact top-k programs.

    ``metric="cosine"`` L2-normalizes corpus rows at upload and queries at
    request time, reducing cosine similarity to the same dot-product
    kernel. Thread model: ``query`` may be called from any handler thread;
    a lock serializes program build + compile bookkeeping (the matmul
    itself is serialized by jax's dispatch anyway).
    """

    def __init__(
        self,
        corpus,
        *,
        metric: str = "dot",
        max_queries: int = 256,
        mesh=None,
        sentry=None,
        metrics=None,
        generation: int = 0,
    ):
        if metric not in METRICS:
            raise ValueError(f"neighbors metric must be one of {METRICS}, got {metric!r}")
        host = np.asarray(corpus, np.float32)
        if host.ndim != 2 or host.shape[0] < 1:
            raise ValueError(f"corpus must be (n >= 1, d), got {host.shape}")
        self.metric = metric
        # which encoder generation embedded this corpus (coscheduler swap
        # tag): a fresh index is built per weight swap and the server's
        # index reference swapped atomically, so /v1/neighbors always
        # answers from the same generation /v1/embed computes with
        self.generation = int(generation)
        self.n, self.d = host.shape
        if metric == "cosine":
            host = _normalize_rows(host)
        self.mesh = mesh if mesh is not None else retrieval_mesh()
        self.n_shards = self.mesh.shape[DATA_AXIS]
        # device-resident, row-sharded over the data axis; the padded tail
        # (put_row_sharded zero-fills to equal shards) is masked to -inf in
        # the kernel so it can never win a top-k slot
        self.corpus = put_row_sharded(host, self.mesh)
        self.rows_per_shard = self.corpus.shape[0] // self.n_shards
        self.max_queries = int(max_queries)
        self.buckets = make_buckets(self.max_queries)
        self.sentry = sentry
        self.metrics = metrics
        self._lock = threading.Lock()
        self._fns: dict[int, object] = {}
        self._compiled: set[tuple[int, int]] = set()
        if metrics is not None and hasattr(metrics, "corpus_hbm_bytes"):
            metrics.corpus_hbm_bytes.set(int(self.corpus.nbytes))

    @classmethod
    def from_file(cls, path: str, **kwargs):
        """Load an ``(n, d)`` corpus from ``.npy`` or ``.npz`` (first array,
        or the ``features`` key — ``eval.save_features`` layout)."""
        path = str(path)
        if path.endswith(".npz"):
            with np.load(path) as z:
                key = "features" if "features" in z.files else z.files[0]
                arr = z[key]
        else:
            arr = np.load(path)
        return cls(arr, **kwargs)

    # -- program construction ----------------------------------------------
    def _fn_for(self, k: int):
        """The jitted shard_map top-k program for one ``k`` (shape-keyed jit
        cache handles the query buckets)."""
        fn = self._fns.get(k)
        if fn is not None:
            return fn
        n, r, kk = self.n, self.rows_per_shard, min(k, self.rows_per_shard)

        def local_merge(q, shard):
            # q: (B, d) replicated; shard: (R, d) this shard's row block
            scores = q @ shard.T  # (B, R) — the only similarity block ever built
            sidx = jax.lax.axis_index(DATA_AXIS)
            global_idx = sidx * r + jnp.arange(r, dtype=jnp.int32)
            scores = jnp.where(global_idx[None, :] < n, scores, -jnp.inf)
            vals, idx = jax.lax.top_k(scores, kk)
            gidx = jnp.take(global_idx, idx)
            # (S, B, kk) -> shard-major (B, S*kk) candidate lists: stable
            # TopK over this layout tie-breaks to the lowest global row id
            vals_all = jax.lax.all_gather(vals, DATA_AXIS)
            gidx_all = jax.lax.all_gather(gidx, DATA_AXIS)
            cand_vals = jnp.moveaxis(vals_all, 0, 1).reshape(q.shape[0], -1)
            cand_idx = jnp.moveaxis(gidx_all, 0, 1).reshape(q.shape[0], -1)
            top_vals, pos = jax.lax.top_k(cand_vals, k)
            top_idx = jnp.take_along_axis(cand_idx, pos, axis=1)
            return top_vals, top_idx

        fn = jax.jit(
            shard_map(
                local_merge,
                mesh=self.mesh,
                in_specs=(P(), P(DATA_AXIS)),
                out_specs=(P(), P()),
                check_vma=False,
            )
        )
        self._fns[k] = fn
        return fn

    def bucket_for(self, n_queries: int) -> int:
        if n_queries < 1:
            raise ValueError(f"need at least one query, got {n_queries}")
        if n_queries > self.max_queries:
            raise ValueError(
                f"{n_queries} queries exceeds the {self.max_queries}-query "
                f"ceiling; split the request"
            )
        for b in self.buckets:
            if b >= n_queries:
                return b
        raise AssertionError("unreachable: buckets end at max_queries")

    def warmup(self, k: int) -> None:
        """Pre-compile every query bucket for one ``k`` (served cold
        otherwise — neighbors compiles never alarm)."""
        for b in self.buckets:
            self._query_padded(np.zeros((b, self.d), np.float32), k, b)

    # -- request path ------------------------------------------------------
    def query(self, queries, k: int) -> tuple[np.ndarray, np.ndarray]:
        """Exact top-``k`` over the corpus; ``(B, k)`` scores + row indices.

        ``queries``: ``(B, d)`` float rows. ``k`` must fit the corpus
        (``1 <= k <= n``) so every returned slot is a real row.
        """
        q = np.asarray(queries, np.float32)
        if q.ndim != 2 or q.shape[1] != self.d:
            raise ValueError(f"queries must be (B, {self.d}), got {q.shape}")
        if not 1 <= int(k) <= self.n:
            raise ValueError(f"k must be in [1, {self.n}] for a {self.n}-row corpus, got {k}")
        k = int(k)
        b = q.shape[0]
        bucket = self.bucket_for(b)
        if self.metric == "cosine":
            q = _normalize_rows(q)
        if b < bucket:
            q = np.concatenate([q, np.zeros((bucket - b, self.d), np.float32)])
        t0 = time.perf_counter()
        vals, idx = self._query_padded(q, k, bucket)
        if self.metrics is not None:
            self.metrics.neighbors_requests_total.inc()
            self.metrics.neighbors_queries_total.inc(b)
            self.metrics.neighbors_latency_ms.observe(
                (time.perf_counter() - t0) * 1000.0
            )
        return np.asarray(vals[:b]), np.asarray(idx[:b], np.int64)

    def _query_padded(self, q: np.ndarray, k: int, bucket: int):
        with self._lock:
            fn = self._fn_for(k)
            cold = (k, bucket) not in self._compiled
            if cold:
                self._compiled.add((k, bucket))
        t0 = time.perf_counter()
        out_vals, out_idx = fn(q, self.corpus)
        vals, idx = fetch(out_vals), fetch(out_idx)
        if cold and self.sentry is not None:
            # warm=False by design: novel (k, bucket) programs are an
            # expected lazy compile, not a broken embed warmup
            self.sentry.record_compile(
                f"neighbors_k{k}_q{bucket}",
                seconds=time.perf_counter() - t0,
                warm=False,
            )
        return vals, idx

    # -- observability ------------------------------------------------------
    def hbm_state(self) -> dict:
        """The /healthz ``neighbors`` entry: corpus residency + programs."""
        return {
            "rows": self.n,
            "dim": self.d,
            "metric": self.metric,
            "generation": self.generation,
            "shards": self.n_shards,
            "rows_per_shard": self.rows_per_shard,
            "corpus_hbm_bytes": int(self.corpus.nbytes),
            "compiled_programs": sorted(self._compiled),
        }
