"""Feature export: clean and augmentation-averaged features as ``.npy``.

TPU-native counterpart of ``/root/reference/save_features.py``: for each
checkpoint in ``experiment.target_dir``,

  * dump clean (no-augmentation) train/val features + labels as four ``.npy``
    files (``save_features.py:152-163``);
  * dump augmentation-averaged train features: a running mean over 20 passes
    of one stochastic SimCLR view, saved at t ∈ {1, 5, 20}
    (``save_features.py:166-179``).

    python -m simclr_tpu.save_features experiment.target_dir=results/...

Uses the eval config (same as the reference, ``save_features.py:119``).
"""

from __future__ import annotations

import math
import os
import sys

import jax
import numpy as np

from simclr_tpu.config import Config, check_save_features_conf, load_config, resolve_save_dir
from simclr_tpu.data.cifar import load_dataset
from simclr_tpu.eval import (
    build_eval_model,
    extract_features,
    load_model_variables,
)
from simclr_tpu.parallel.mesh import (
    batch_sharding,
    mesh_from_config,
    process_local_rows,
    put_global_batch,
    validate_per_device_batch,
)
from simclr_tpu.parallel.steps import make_augmented_encode_step
from simclr_tpu.utils.checkpoint import list_checkpoints_or_raise
from simclr_tpu.utils.fetch import fetch
from simclr_tpu.utils.ioutil import atomic_write
from simclr_tpu.utils.logging import get_logger, is_logging_host

logger = get_logger()

# reference: 20 passes, snapshots at 1/5/20 (save_features.py:166-179)
NUM_AUGMENTATIONS = 20
SNAPSHOT_PASSES = (1, 5, 20)


def augmented_features(
    model, variables, images: np.ndarray, mesh, batch: int, strength: float,
    seed: int, num_passes: int, snapshots: tuple[int, ...],
    use_full_encoder: bool = False,
) -> dict[int, np.ndarray]:
    """Running mean of single-view augmented features, snapshotted at
    ``snapshots`` pass counts (``/root/reference/save_features.py:166-179``)."""
    encode = make_augmented_encode_step(
        model, mesh, strength=strength, use_full_encoder=use_full_encoder
    )
    sharding = batch_sharding(mesh)
    n = len(images)
    steps = math.ceil(n / batch)
    pad = steps * batch - n
    padded = (
        np.concatenate([images, np.zeros((pad, *images.shape[1:]), images.dtype)])
        if pad
        else images
    )
    local = process_local_rows(batch)  # multi-host: upload only this
    # process's row block of each chunk (see eval.extract_features)
    mean = None
    out: dict[int, np.ndarray] = {}
    for t in range(1, num_passes + 1):
        feats = []
        for i in range(steps):
            chunk = put_global_batch(padded[i * batch : (i + 1) * batch][local], sharding)
            rng = jax.random.fold_in(jax.random.key(seed), t * steps + i)
            # dispatch only; the device->host sync happens once per pass so
            # upload/compute pipeline across chunks (see eval.extract_features)
            feats.append(encode(variables["params"], variables["batch_stats"], chunk, rng))
        pass_feats = np.concatenate([fetch(f) for f in feats])[:n]
        mean = pass_feats if mean is None else mean + (pass_feats - mean) / t
        if t in snapshots:
            out[t] = mean.copy()
    return out


def run_save_features(cfg: Config) -> list[str]:
    check_save_features_conf(cfg)
    mesh = mesh_from_config(cfg)
    synthetic_ok = bool(cfg.select("experiment.synthetic_data", False))
    data_dir = cfg.select("experiment.data_dir")
    train_ds = load_dataset(
        cfg.experiment.name, "train", data_dir=data_dir, synthetic_ok=synthetic_ok,
        synthetic_size=cfg.select("experiment.synthetic_size"),
        synthetic_noise=cfg.select("experiment.synthetic_noise"),
    )
    val_ds = load_dataset(
        cfg.experiment.name, "test", data_dir=data_dir, synthetic_ok=synthetic_ok,
        synthetic_size=cfg.select("experiment.synthetic_size"),
        synthetic_noise=cfg.select("experiment.synthetic_noise"),
    )

    model = build_eval_model(cfg)
    batch = validate_per_device_batch(int(cfg.experiment.batches), mesh)
    use_full_encoder = bool(cfg.parameter.use_full_encoder)
    strength = float(cfg.select("experiment.strength", 0.5))
    seed = int(cfg.parameter.seed)
    out_dir = resolve_save_dir(cfg)
    if is_logging_host():
        os.makedirs(out_dir, exist_ok=True)

    written: list[str] = []

    def save(name: str, array: np.ndarray) -> None:
        path = os.path.join(out_dir, name)
        if is_logging_host():
            # atomic: a SIGKILL mid-write must not leave a truncated .npy
            # that the resume existence-gate would then carry forward as
            # complete. The file-object form keeps np.save from appending
            # a second .npy suffix to the tmp name.
            atomic_write(path, lambda f: np.save(f, array), mode="wb")
        written.append(path)

    checkpoints = list_checkpoints_or_raise(str(cfg.experiment.target_dir))

    # experiment.resume=true: skip checkpoints whose full export set already
    # exists — a crashed multi-checkpoint export (20 augmentation passes per
    # checkpoint are the expensive part) resumes at checkpoint granularity.
    # Improvement over the reference (redoes everything, save_features.py).
    # Multi-process: out_dir must be a shared filesystem so every process
    # makes the SAME skip decision (only process 0 writes; a per-host local
    # out_dir would desynchronize the collective extract path) — the same
    # contract as checkpoint and eval-sweep resume.
    resume = bool(cfg.select("experiment.resume", False))

    for ckpt in checkpoints:
        key = os.path.basename(ckpt)
        expected = [
            f"{key}.train.features.npy", f"{key}.train.labels.npy",
            f"{key}.val.features.npy", f"{key}.val.labels.npy",
        ] + [f"{key}.train.aug-{t}.features.npy" for t in SNAPSHOT_PASSES]
        if resume and all(
            os.path.exists(os.path.join(out_dir, p)) for p in expected
        ):
            logger.info("Skipping %s (features already exported)", key)
            written.extend(os.path.join(out_dir, p) for p in expected)
            continue
        logger.info("Extracting features with %s", key)
        variables = load_model_variables(ckpt)

        # clean features, train + val (reference save_features.py:152-163)
        train_X = extract_features(
            model, variables, train_ds.images, mesh, batch, use_full_encoder
        )
        val_X = extract_features(
            model, variables, val_ds.images, mesh, batch, use_full_encoder
        )
        save(f"{key}.train.features.npy", train_X)
        save(f"{key}.train.labels.npy", train_ds.labels)
        save(f"{key}.val.features.npy", val_X)
        save(f"{key}.val.labels.npy", val_ds.labels)

        # augmentation-averaged train features (save_features.py:166-179)
        snapshots = augmented_features(
            model, variables, train_ds.images, mesh, batch, strength, seed,
            NUM_AUGMENTATIONS, SNAPSHOT_PASSES, use_full_encoder,
        )
        for t, mean in snapshots.items():
            save(f"{key}.train.aug-{t}.features.npy", mean)

    return written


def main(argv: list[str] | None = None):
    from simclr_tpu.parallel.multihost import maybe_initialize_multihost
    from simclr_tpu.utils.platform import ensure_platform

    ensure_platform()
    maybe_initialize_multihost()
    from simclr_tpu.config import run_multirun, split_multirun_flag

    multirun, args = split_multirun_flag(list(sys.argv[1:] if argv is None else argv))
    if multirun:
        return run_multirun(run_save_features, "eval", args)
    cfg = load_config("eval", overrides=args)
    return run_save_features(cfg)


if __name__ == "__main__":
    main()
